"""Fleet HTTP frontend: one streaming endpoint over N replicas.

Wire-compatible with the single-replica ``serve/server.py`` — same
``POST /generate`` ndjson stream, same sampling knobs — so clients
(and ``bench_serve.py``) point at a fleet without changes. What
differs is behind the socket:

* ``/generate`` submits a :class:`~horovod_tpu.serve.fleet.router.
  FleetRequest`: the router picks the replica, and if that replica is
  preempted mid-stream the client's connection NEVER sees it — the
  continuation re-dispatch keeps the same ndjson stream flowing from
  a survivor.
* ``/healthz`` is fleet-shaped: aggregate status (``ok`` while at
  least one replica admits, ``draining`` while all live replicas are
  refusing admission, ``down`` when none is left), router queue
  depth, re-dispatch/drop counters, and the per-replica health dict
  each replica's own ``/healthz`` would report.
* ``/metrics`` renders the shared registry — per-replica
  ``hvd_serve_queue_depth{replica=...}`` / ``hvd_serve_kv_blocks``
  children plus the fleet's ``hvd_serve_replicas{state=...}``.
"""

import json
import logging

from horovod_tpu.serve import engine as engine_lib
from horovod_tpu.serve.fleet.router import FleetRequest
from horovod_tpu.serve.sampling import SamplingParams
from horovod_tpu.telemetry.registry import get_registry
from horovod_tpu.utils.httpd import HttpService, QuietHandler

logger = logging.getLogger("horovod_tpu")

MAX_BODY = 8 << 20


class FleetServer(HttpService):
    """The generate frontend over one :class:`FleetRouter`. ``port=0``
    binds an ephemeral port (in ``.port`` after ``start()``)."""

    thread_name = "hvd_fleet_http"

    def __init__(self, router, addr="127.0.0.1", port=0, registry=None,
                 stream_timeout=300.0):
        super().__init__(addr=addr, port=port)
        self.router = router
        self.registry = (registry if registry is not None
                         else getattr(router, "registry", None))
        if self.registry is None:
            self.registry = get_registry()
        self._stream_timeout = float(stream_timeout)

    def _handler_class(self):
        server = self

        class Handler(QuietHandler):
            log_name = "fleet"

            def do_GET(self):
                try:
                    if self.path == "/healthz":
                        body = server.router.healthz()
                        self._respond_json(
                            200 if body["status"] == "ok" else 503,
                            body)
                    elif self.path == "/metrics":
                        self._respond(
                            200, server.registry.render_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    else:
                        self._respond(404, "not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                # hvd-lint: disable=HVD-EXCEPT -- keep the plane up; the handler reports 500 below
                except Exception as e:
                    logger.warning("fleet endpoint %s failed: %s",
                                   self.path, e)
                    try:
                        self._respond(500, f"{e}\n", "text/plain")
                    # hvd-lint: disable=HVD-EXCEPT -- the client is gone; nothing left to report to
                    except Exception:
                        pass

            def do_POST(self):
                if self.path != "/generate":
                    return self._respond(404, "not found\n", "text/plain")
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    if length <= 0 or length > MAX_BODY:
                        return self._respond_json(
                            400, {"error": "body required (JSON, "
                                           f"<= {MAX_BODY} bytes)"})
                    try:
                        body = json.loads(self.rfile.read(length))
                        tokens = body["tokens"]
                        if (not isinstance(tokens, list)
                                or not all(isinstance(t, int)
                                           for t in tokens)):
                            raise ValueError(
                                "tokens must be a list of ints")
                        sp = None
                        if any(k in body for k in ("temperature",
                                                   "top_p", "seed")):
                            sp = SamplingParams(
                                temperature=float(
                                    body.get("temperature", 0.0)),
                                top_p=float(body.get("top_p", 1.0)),
                                seed=int(body.get("seed", 0)))
                        freq = FleetRequest(
                            tokens, int(body.get("max_new_tokens", 16)),
                            eos_id=body.get("eos_id"), sampling=sp,
                            trace=bool(body.get("trace", False)))
                    except (KeyError, ValueError, TypeError) as e:
                        return self._respond_json(400, {"error": str(e)})
                    try:
                        server.router.submit(freq)
                    except engine_lib.RequestError as e:
                        return self._respond_json(400, {"error": str(e)})
                    self._stream(freq)
                except BrokenPipeError:
                    pass  # client went away; the fleet finishes anyway
                # hvd-lint: disable=HVD-EXCEPT -- keep the plane up; the handler reports 500 below
                except Exception as e:
                    logger.warning("fleet /generate failed: %s", e)
                    try:
                        self._respond(500, f"{e}\n", "text/plain")
                    # hvd-lint: disable=HVD-EXCEPT -- the client is gone; nothing left to report to
                    except Exception:
                        pass

            def _stream(self, freq):
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Connection", "close")
                self.end_headers()

                def line(obj):
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()

                tr = freq.trace
                first = tr is not None
                try:
                    for tok in freq.stream(
                            timeout=server._stream_timeout):
                        if first:
                            # best-effort first-byte span (the trace
                            # may finalize before the stream drains)
                            first = False
                            t0 = tr.now()
                            line({"token": tok})
                            tr.span("stream", t0, tr.now(),
                                    actor="http", first_byte=True)
                        else:
                            line({"token": tok})
                    line({"done": True, "tokens": freq.generated,
                          "finish_reason": freq.finish_reason,
                          "hops": freq.hops})
                except (engine_lib.RequestError, TimeoutError) as e:
                    line({"error": str(e)})

        return Handler

    def start(self):
        port = super().start()
        logger.info("fleet endpoint on http://%s:%d/generate "
                    "(%d replicas)", self._addr, port,
                    len(self.router.replicas))
        return port
