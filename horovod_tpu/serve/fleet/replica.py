"""One fleet replica: a named engine, its state, its preemption arm.

The replica is the unit of capacity AND the unit of failure: spot
preemption, chaos eviction, and rolling weight staging all happen to
one replica while the rest of the fleet keeps admitting. State
transitions are one-way in the failure direction (``ready`` →
``draining`` → ``dead``) except staging, which drains briefly and
returns to ready.

Spot capacity reuses ``elastic/preempt.py`` wholesale: the replica
arms a :class:`~horovod_tpu.elastic.preempt.GracefulEvictionHandler`
whose *bounded force-commit* is the traffic drain (the handler calls
``state.flush(timeout=grace)``; here the "state" being committed is
the replica's in-flight requests) and whose *exit* is the router's
eviction callback instead of ``os._exit``. Notice sources (the
per-replica spot notice file / URL), the grace budget, the doomed-host
announce, ``hvd_preemptions_total{kind}`` and
``hvd_grace_commit_seconds`` all come along unchanged — one eviction
machinery for the training and serving planes.
"""

import logging
import time

from horovod_tpu.elastic import preempt as preempt_lib

logger = logging.getLogger("horovod_tpu")

READY = "ready"
DRAINING = "draining"
DEAD = "dead"
STATES = (READY, DRAINING, DEAD)


class _DrainAsState:
    """Adapter: the eviction handler force-commits whatever its
    ``state.flush(timeout=...)`` does — for a serving replica that is
    "drain my in-flight traffic within the grace budget"."""

    def __init__(self, drain_fn):
        self._drain = drain_fn

    def flush(self, timeout=None):
        self._drain(timeout)


class Replica:
    """One engine in the fleet. The router owns the state machine;
    this class owns the engine handle and the preempt arm."""

    def __init__(self, name, engine, clock=time.monotonic):
        self.name = str(name)
        self.engine = engine
        self.state = READY
        self.stopped_at = None  # clock() when the engine was stopped
        # clock() when the last drain (preempt, chaos, weight staging)
        # began — the start of the window request traces overlap and
        # hvd_serve_weight_swap_seconds measures for a rolling reload
        self.drain_started_at = None
        self._clock = clock
        self._handler = None

    # -- dispatch inputs -----------------------------------------------------
    @property
    def load(self):
        """Queued + running requests — the queue-depth half of the
        router's dispatch score."""
        return self.engine.queue_depth + self.engine.active_count

    def headroom_for(self, need_blocks):
        """True when the replica could cover a ``need_blocks`` KV
        reservation: free blocks plus the prefix cache's RECLAIMABLE
        claim (engine admission releases cache LRU under pressure).
        Only sole-reference cache entries count — an entry a live
        sequence also maps frees no pool block when released, so
        counting it would score headroom the replica doesn't have."""
        reclaimable = (self.engine.prefix_cache.reclaimable()
                       if self.engine.prefix_cache is not None else 0)
        return (self.engine.allocator.available + reclaimable
                >= need_blocks)

    def health(self):
        """The per-replica ``/healthz`` shape (serve/server.py), as the
        fleet frontend aggregates it."""
        eng = self.engine
        return {
            "state": self.state,
            "queue_depth": eng.queue_depth,
            "active": eng.active_count,
            "kv_blocks_in_use": eng.allocator.in_use,
            "kv_blocks_free": eng.allocator.available,
            "prefix_cache_blocks": (eng.prefix_cache.size
                                    if eng.prefix_cache is not None
                                    else 0),
            "weights_version": eng.weights_version,
            "drain_started_at": self.drain_started_at,
        }

    # -- spot preemption -----------------------------------------------------
    def arm_preempt(self, on_drain, on_evict, notice_file=None,
                    notice_url=None, grace=None, poll_interval=None,
                    env=None):
        """Arm the graceful-eviction machinery for this replica.
        ``on_drain(timeout)`` runs inside the grace window (the
        router's traffic drain); ``on_evict()`` replaces process exit.
        With a notice source the handler's poller watches it; without
        one the handler is trigger-only (the router's ``preempt()``
        and the chaos harness drive it)."""
        if self._handler is not None:
            return self._handler
        self._handler = preempt_lib.GracefulEvictionHandler(
            state=_DrainAsState(on_drain),
            grace=grace, notice_file=notice_file, notice_url=notice_url,
            poll_interval=poll_interval, clock=self._clock,
            exit_fn=lambda code: on_evict(), env=env)
        if notice_file or notice_url:
            self._handler.install()
        return self._handler

    def trigger_preempt(self, kind="notice:router"):
        """Start this replica's eviction (idempotent). Returns the
        eviction thread, or None when none is armed / already run."""
        if self._handler is None:
            return None
        return self._handler.trigger(kind)

    def disarm(self):
        if self._handler is not None:
            self._handler.uninstall()
            self._handler = None
