"""The serve fleet: N engine replicas behind one routing frontend.

One :class:`~horovod_tpu.serve.engine.ServeEngine` answers requests;
this package is what turns a set of them into a SERVICE (ROADMAP
north star, "heavy traffic"):

* **replica** (``replica.py``) — one named engine plus its lifecycle
  state (``ready`` / ``draining`` / ``dead``) and, on spot capacity,
  its armed preemption handler — the ``elastic/preempt.py`` machinery
  (notice polling, grace budget, announce, ``hvd_preemptions_total``)
  pointed at traffic drain instead of checkpoint commit;
* **router** (``router.py``) — queue-depth- and KV-headroom-aware
  dispatch over the ready replicas, fleet-wide rolling weight reload
  (one replica staged at a time, so the fleet never has zero admitting
  replicas), and the zero-drop eviction path: a request cut off by a
  dying replica is re-dispatched to a survivor as a CONTINUATION
  (``prompt + tokens generated so far``), which the position-keyed
  sampling of ``serve/sampling.py`` makes stream-transparent — the
  client sees one uninterrupted, seed-deterministic token stream;
* **frontend** (``frontend.py``) — the one streaming HTTP endpoint in
  front of the fleet, same wire protocol as the single-replica
  ``serve/server.py`` plus fleet-shaped ``/healthz``.

Replicas here are in-process (each engine already owns its mesh
placement, pool, and scheduler thread); the router/replica split is
what a multi-host deployment would put a network between, and
everything the router consumes (health state, queue depth, KV
headroom, weights version) is exactly what the per-replica
``/healthz`` already reports. docs/SERVING.md, "Serve fleet".
"""

from horovod_tpu.serve.fleet.frontend import FleetServer  # noqa: F401
from horovod_tpu.serve.fleet.replica import Replica  # noqa: F401
from horovod_tpu.serve.fleet.router import (  # noqa: F401
    FleetRequest,
    FleetRouter,
)

__all__ = ["Replica", "FleetRouter", "FleetRequest", "FleetServer"]
