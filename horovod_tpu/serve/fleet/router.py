"""Fleet router: dispatch, drain, re-dispatch — zero dropped requests.

The router is a thin, deliberately boring layer: all batching
intelligence lives in the engines; the router only decides WHICH
engine, and owns the failure story. Three mechanisms:

* **Dispatch** — a background dispatcher pulls queued
  :class:`FleetRequest`\\ s and scores every ``ready`` replica by
  ``(has KV headroom, queue depth + active, -free blocks)``: KV
  headroom first (a request that cannot reserve its blocks would sit
  in engine backpressure while another replica could run it NOW), load
  second, free pool as the tiebreak. A request a draining/dead replica
  refuses is simply scored elsewhere; with no live replica at all the
  queue waits (capacity may return) unless every replica is ``dead``.
* **Drain** (spot preemption, chaos eviction, weight staging) — the
  doomed replica flips to ``draining``: the engine refuses new
  admissions (503 ``draining`` on its ``/healthz``), the router stops
  dispatching to it, and in-flight sequences run to completion inside
  the grace budget. Whatever is still unfinished at eviction fails
  over to the re-dispatch path.
* **Re-dispatch** — a request cut mid-stream by an eviction is NOT an
  error the client sees: the router resubmits it to a survivor as a
  continuation (``prompt + generated so far``, remaining token
  budget). Greedy decoding is trivially resumable; sampled decoding
  resumes EXACTLY because ``serve/sampling.py`` keys every token on
  ``(seed, absolute position)`` — the continuation's next token draws
  the same RNG key it would have drawn on the dead replica. The
  client's stream just keeps going; ``hvd_serve_requests_total``
  counts the hop under ``redispatched``, not ``failed``.

Rolling weight reload composes the same drain: ``install_weights``
stages one replica at a time (drain → stage → swap → ready), so a
checkpoint roll never leaves the fleet without an admitting replica —
``serve/loader.ReloadWatcher`` can point at the router exactly as it
would at a single engine.
"""

import itertools
import logging
import queue
import threading
import time
from collections import OrderedDict, deque

from horovod_tpu.serve import engine as engine_lib
from horovod_tpu.serve import sampling as sampling_lib
from horovod_tpu.serve.fleet import replica as replica_lib
from horovod_tpu.telemetry import instruments as instruments_lib
from horovod_tpu.telemetry.registry import get_registry

logger = logging.getLogger("horovod_tpu")

# engine refusals that mean "try another replica", not "bad request"
_RETRYABLE = ("draining", "stopped", "dispatch failed")


def _retryable(message):
    return any(marker in str(message) for marker in _RETRYABLE)


class FleetRequest:
    """A client request at fleet scope: same event-queue stream
    protocol as the engine's :class:`~horovod_tpu.serve.engine.
    Request`, but it survives its current engine — the router may play
    it through several replicas; ``generated`` accumulates across
    hops and the stream never repeats or skips a token."""

    _ids = itertools.count()

    def __init__(self, tokens, max_new_tokens, eos_id=None,
                 sampling=None, request_id=None, trace=False):
        self.id = (f"fleet-{next(self._ids)}" if request_id is None
                   else request_id)
        self.prompt = [int(t) for t in tokens]
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.sampling = (sampling_lib.GREEDY if sampling is None
                         else sampling)
        self.generated = []
        self.state = "new"  # new|queued|running|done|failed
        self.finish_reason = None
        self.error = None
        self.replica = None     # replica currently (last) running it
        self.hops = 0           # re-dispatches survived
        # client-observable latency (what a caller on the other side
        # of the frontend would measure — TTFT spans router queueing,
        # dispatch, engine queueing AND any re-dispatch). Stamped with
        # the ROUTER's clock (installed at submit) so fake-clock tests
        # and benches see one time base fleet-wide.
        self.arrival = None
        self.admitted_at = None  # first engine admission (TTFT base 2)
        self.first_token_time = None
        self.token_times = []
        # request-scoped tracing (serve/tracing.py): the router owns a
        # fleet request's trace for its WHOLE life — the same
        # RequestTrace rides every per-hop engine request, so a cut and
        # its continuation land on one timeline
        self.trace_requested = bool(trace)
        self.trace = None
        self._trace_owned = False
        self._clock = time.monotonic
        self._events = queue.Queue()

    def _emit(self, kind, value=None):
        if kind == "token":
            now = self._clock()
            if self.first_token_time is None:
                self.first_token_time = now
            self.token_times.append(now)
        self._events.put((kind, value))

    def stream(self, timeout=120.0):
        """Yield token ids until done. Raises
        :class:`~horovod_tpu.serve.engine.RequestError` on terminal
        failure, ``TimeoutError`` on fleet silence."""
        while True:
            try:
                kind, value = self._events.get(timeout=timeout)
            except queue.Empty:
                raise TimeoutError(
                    f"request {self.id}: no event for {timeout:.0f}s "
                    f"(state {self.state})") from None
            if kind == "token":
                yield value
            elif kind == "done":
                return
            else:
                raise engine_lib.RequestError(value)

    def result(self, timeout=120.0):
        return list(self.stream(timeout=timeout))


class FleetRouter:
    """Replica registry + dispatcher + failure handling (module
    docstring). ``clock`` is injectable like the engine's. Replicas
    are added ready; :meth:`submit`/:meth:`generate` are the client
    surface, :meth:`drain`/:meth:`evict`/:meth:`preempt` the
    lifecycle surface, :meth:`install_weights` the reload surface."""

    def __init__(self, registry=None, clock=time.monotonic,
                 grace=None, stream_timeout=120.0,
                 stage_timeout=30.0, tracer=None):
        self.registry = registry if registry is not None \
            else get_registry()
        self._clock = clock
        self._tracer = tracer
        self._grace = grace
        self._stream_timeout = float(stream_timeout)
        self._stage_timeout = float(stage_timeout)
        self._replicas = OrderedDict()  # name -> Replica
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue = deque()
        self._stop_evt = threading.Event()
        self._thread = None
        self._replica_gauge = instruments_lib.serve_replicas_gauge(
            self.registry)
        self._requests = self.registry.counter(
            instruments_lib.SERVE_REQUESTS,
            "Generate requests by lifecycle event (submitted / "
            "completed / failed)", label_names=("event",))
        self._redispatch_counter = \
            instruments_lib.serve_redispatch_counter(self.registry)
        self._swap_seconds = \
            instruments_lib.serve_weight_swap_histogram(self.registry)
        self.redispatched = 0  # request hops survived (not failures)
        self.dropped = 0       # terminally failed AFTER running (SLO: 0)

    # -- replica registry ----------------------------------------------------
    def add_replica(self, name, engine, notice_file=None,
                    notice_url=None, grace=None, poll_interval=None,
                    env=None):
        """Register an engine as a fleet replica and arm its
        preemption handler (always armed — chaos and the ``preempt``
        API drive unarmed-by-notice replicas via ``trigger``)."""
        with self._lock:
            if name in self._replicas:
                raise ValueError(f"duplicate replica name {name!r}")
            rep = replica_lib.Replica(name, engine, clock=self._clock)
            rep.arm_preempt(
                on_drain=lambda timeout, n=name: self.drain_traffic(
                    n, grace=timeout),
                on_evict=lambda n=name: self.evict(n),
                notice_file=notice_file, notice_url=notice_url,
                grace=grace if grace is not None else self._grace,
                poll_interval=poll_interval, env=env)
            self._replicas[name] = rep
            self._update_replica_gauge()
            self._cond.notify_all()
        return rep

    def replica(self, name):
        return self._replicas[name]

    @property
    def replicas(self):
        return list(self._replicas.values())

    def _update_replica_gauge(self):
        counts = {s: 0 for s in replica_lib.STATES}
        for rep in self._replicas.values():
            counts[rep.state] += 1
        for state, n in counts.items():
            self._replica_gauge.labels(state).set(n)

    # -- client surface ------------------------------------------------------
    def submit(self, request):
        """Queue a :class:`FleetRequest` for dispatch; returns it."""
        with self._cond:
            if self._stop_evt.is_set():
                request.state = "failed"
                request.error = "fleet router is stopped"
                request._emit("error", request.error)
                raise engine_lib.RequestError(request.error)
            request.state = "queued"
            request._clock = self._clock
            request.arrival = self._clock()
            if request.trace is None and self._tracer is not None:
                tr = self._tracer.begin(request.id,
                                        force=request.trace_requested)
                if tr is not None:
                    request.trace = tr
                    request._trace_owned = True
            if request.trace is not None:
                request.trace.phase(request.arrival, "queued")
                request.trace.event("submit", request.arrival,
                                    actor="router")
            self._queue.append(request)
            self._cond.notify_all()
        return request

    def generate(self, tokens, max_new_tokens, eos_id=None,
                 sampling=None):
        return self.submit(FleetRequest(tokens, max_new_tokens,
                                        eos_id=eos_id,
                                        sampling=sampling))

    # -- dispatch ------------------------------------------------------------
    def _pick(self, freq):
        """Best ready replica for this request: KV headroom beats
        load beats free-pool size. None when nobody is ready."""
        ready = [r for r in self._replicas.values()
                 if r.state == replica_lib.READY]
        if not ready:
            return None
        need = None
        best, best_score = None, None
        for rep in ready:
            need = rep.engine.blocks_needed(len(freq.prompt)
                                            + len(freq.generated),
                                            freq.max_new_tokens
                                            - len(freq.generated))
            score = (0 if rep.headroom_for(need) else 1,
                     rep.load, -rep.engine.allocator.available)
            if best_score is None or score < best_score:
                best, best_score = rep, score
        return best

    def _dispatch(self, freq):
        """Submit ``freq``'s (continuation) engine request to the best
        replica and start its pump. Returns False when no ready
        replica exists (requeue); terminal failures are handled."""
        remaining = freq.max_new_tokens - len(freq.generated)
        tr = freq.trace
        while True:
            t_pick = self._clock() if tr is not None else 0.0
            with self._lock:
                rep = self._pick(freq)
                all_dead = all(r.state == replica_lib.DEAD
                               for r in self._replicas.values())
            if rep is None:
                if self._replicas and all_dead:
                    self._fail(freq, "no live replica in the fleet")
                    return True
                return False
            ereq = engine_lib.Request(
                freq.prompt + freq.generated, remaining,
                eos_id=freq.eos_id, sampling=freq.sampling)
            # the fleet trace rides every per-hop engine request, so
            # engine spans (admission, prefill chunks, decode batches)
            # land on the one fleet timeline
            ereq.trace = tr
            try:
                rep.engine.submit(ereq)
            except engine_lib.RequestError as e:
                if _retryable(e):
                    # a replica the router believes ready but whose
                    # engine is gone (broken program, stopped) will
                    # refuse forever — retire it so the re-pick
                    # converges instead of spinning on the same score
                    if (rep.engine._broken is not None
                            or rep.engine._stop.is_set()):
                        self.evict(rep.name)
                    continue  # replica flipped under us; score again
                self._fail(freq, str(e))
                return True
            freq.state = "running"
            freq.replica = rep.name
            if tr is not None:
                tr.span("dispatch", t_pick, self._clock(),
                        actor="router", replica=rep.name, hop=freq.hops)
            pump = threading.Thread(
                target=self._pump, args=(freq, ereq),
                name=f"hvd_fleet_pump_{freq.id}", daemon=True)
            pump.start()
            return True

    def _loop(self):
        while not self._stop_evt.is_set():
            with self._cond:
                while not self._queue and not self._stop_evt.is_set():
                    self._cond.wait(timeout=0.1)
                if self._stop_evt.is_set():
                    return
                freq = self._queue.popleft()
            if not self._dispatch(freq):
                # nobody ready right now — requeue at the FRONT (FIFO
                # fairness for the interrupted) and let states settle
                with self._cond:
                    self._queue.appendleft(freq)
                    self._cond.wait(timeout=0.02)

    def _pump(self, freq, ereq):
        """Forward one engine run's tokens into the fleet request;
        on a retryable failure, hand the remainder back to the
        dispatcher as a continuation."""
        try:
            first = True
            for tok in ereq.stream(timeout=self._stream_timeout):
                if first:
                    first = False
                    if freq.admitted_at is None:
                        freq.admitted_at = ereq.admitted_at
                    if freq.trace is not None and freq.hops:
                        # first token after a hop closes its window
                        freq.trace.event("resumed", self._clock(),
                                         actor=freq.replica or "",
                                         hop=freq.hops)
                freq.generated.append(tok)
                freq._emit("token", tok)
            self._finish(freq, ereq.finish_reason)
        except engine_lib.RequestError as e:
            if _retryable(e):
                self._continue_elsewhere(freq)
            else:
                self._fail(freq, str(e))
        except TimeoutError as e:
            # a silent engine is as dead as a stopped one
            self._continue_elsewhere(freq, note=str(e))

    def _continue_elsewhere(self, freq, note=None):
        remaining = freq.max_new_tokens - len(freq.generated)
        if remaining <= 0:
            self._finish(freq, "length")
            return
        if (freq.eos_id is not None and freq.generated
                and freq.generated[-1] == freq.eos_id):
            self._finish(freq, "eos")
            return
        with self._cond:
            if self._stop_evt.is_set():
                self._fail(freq, "fleet router is stopped")
                return
            freq.hops += 1
            self.redispatched += 1
            self._requests.labels("redispatched").inc()
            self._redispatch_counter.inc()
            if freq.trace is not None:
                now = self._clock()
                freq.trace.phase(now, "redispatching")
                attrs = {"note": note} if note else {}
                freq.trace.event("cut", now, actor=freq.replica or "",
                                 hop=freq.hops, **attrs)
            freq.state = "queued"
            self._queue.appendleft(freq)
            self._cond.notify_all()
        logger.info("fleet: request %s re-dispatched (hop %d, %d/%d "
                    "tokens done%s)", freq.id, freq.hops,
                    len(freq.generated), freq.max_new_tokens,
                    f"; {note}" if note else "")

    def _finish(self, freq, reason):
        freq.state = "done"
        freq.finish_reason = reason
        freq._emit("done")
        self._finish_trace(freq, "done", reason=reason)

    def _fail(self, freq, message):
        # a drop is a request the fleet ACCEPTED and then lost: it ran
        # (or survived a hop) and still failed — queued-never-ran
        # refusals are load shedding, not drops
        if freq.state == "running" or freq.generated or freq.hops:
            self.dropped += 1
        freq.state = "failed"
        freq.error = message
        freq._emit("error", message)
        self._finish_trace(freq, "failed", error=message)

    def _finish_trace(self, freq, outcome, **attrs):
        tr = freq.trace
        if tr is None:
            return
        now = self._clock()
        tr.event(outcome, now, actor="router", **attrs)
        if freq._trace_owned:
            freq._trace_owned = False
            if self._tracer is not None:
                self._tracer.finish(tr, end=now)

    # -- lifecycle: drain / evict / preempt ----------------------------------
    def drain_traffic(self, name, grace=None):
        """The in-grace-window drain: stop dispatch + admission to
        ``name``, then wait (bounded) for its in-flight sequences to
        finish. Called by the preemption handler as its force-commit;
        callable directly for a planned drain."""
        rep = self._replicas[name]
        with self._lock:
            if rep.state == replica_lib.DEAD:
                return
            rep.state = replica_lib.DRAINING
            rep.engine.set_draining(True)
            rep.drain_started_at = self._clock()
            self._update_replica_gauge()
        budget = grace if grace is not None else \
            (self._grace if self._grace is not None else 30.0)
        deadline = self._clock() + max(0.0, float(budget))
        while self._clock() < deadline:
            if rep.engine.active_count == 0:
                break
            time.sleep(0.01)
        remaining = rep.engine.active_count
        if remaining == 0:
            logger.info("fleet: replica %s drained within its grace "
                        "budget", name)
        else:
            logger.warning("fleet: replica %s grace budget expired "
                           "with %d still in flight (they fail over "
                           "to re-dispatch at eviction)", name,
                           remaining)

    def evict(self, name):
        """Kill the replica NOW. In-flight/queued engine requests fail
        over to the re-dispatch path — their pumps see the engine-
        stopped error and queue continuations."""
        rep = self._replicas[name]
        with self._lock:
            if rep.state == replica_lib.DEAD:
                return
            rep.state = replica_lib.DEAD
            self._update_replica_gauge()
        rep.engine.stop()
        rep.stopped_at = self._clock()
        with self._cond:
            self._cond.notify_all()
        logger.warning("fleet: replica %s evicted", name)

    def preempt(self, name, kind="notice:router"):
        """Deliver a preemption notice to ``name``: the armed
        ``elastic/preempt.py`` handler runs the full graceful path
        (grace-bounded drain as its force-commit, metrics, then
        eviction). Returns the eviction thread."""
        thread = self._replicas[name].trigger_preempt(kind)
        if thread is None:  # already evicting/evicted
            return None
        return thread

    # -- rolling weight reload ----------------------------------------------
    @property
    def weights_version(self):
        versions = [r.engine.weights_version
                    for r in self._replicas.values()
                    if r.state != replica_lib.DEAD]
        return min((v for v in versions if v is not None), default=None)

    def install_weights(self, params, version=None):
        """Fleet-wide rolling reload: one replica at a time drains
        admission, stages, swaps, and returns to ready — the duck-type
        ``serve/loader.ReloadWatcher`` expects, so one watcher rolls
        the whole fleet."""
        for name, rep in list(self._replicas.items()):
            if rep.state != replica_lib.READY:
                continue  # draining/dead replicas are not staged
            t_roll = self._clock()
            with self._lock:
                rep.state = replica_lib.DRAINING
                rep.engine.set_draining(True)
                rep.drain_started_at = t_roll
                self._update_replica_gauge()
            try:
                rep.engine.install_weights(params, version=version)
                if version is not None:
                    deadline = self._clock() + self._stage_timeout
                    while (rep.engine.weights_version != version
                           and self._clock() < deadline):
                        time.sleep(0.005)
            finally:
                with self._lock:
                    if rep.state == replica_lib.DRAINING:
                        rep.state = replica_lib.READY
                        rep.engine.set_draining(False)
                        self._update_replica_gauge()
                # the whole drain -> stage -> swap -> ready window this
                # replica was out of rotation — the rolling-reload
                # stall /metrics can show (the engine separately
                # observes its in-step swap application)
                self._swap_seconds.observe(self._clock() - t_roll)
                with self._cond:
                    self._cond.notify_all()
            logger.info("fleet: replica %s rolled to weights version "
                        "%s", name, rep.engine.weights_version)

    # -- fleet health --------------------------------------------------------
    def healthz(self):
        replicas = {name: rep.health()
                    for name, rep in self._replicas.items()}
        ready = sum(1 for r in self._replicas.values()
                    if r.state == replica_lib.READY)
        status = "ok" if ready else (
            "down" if not self._replicas or all(
                r.state == replica_lib.DEAD
                for r in self._replicas.values()) else "draining")
        with self._lock:
            depth = len(self._queue)
        return {"status": status, "ready_replicas": ready,
                "router_queue_depth": depth,
                "weights_version": self.weights_version,
                "redispatched": self.redispatched,
                "dropped": self.dropped, "replicas": replicas}

    # -- run loop ------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop_evt.clear()
        for rep in self._replicas.values():
            rep.engine.start()
        self._thread = threading.Thread(target=self._loop,
                                        name="hvd_fleet_router",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        """Stop dispatching, disarm preempt handlers, stop engines.
        Queued fleet requests fail loudly (stream-side too)."""
        self._stop_evt.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        with self._lock:
            pending = list(self._queue)
            self._queue.clear()
        for freq in pending:
            self._fail(freq, "fleet router is stopped")
        # reverse arm order restores any chained signal handlers clean
        for rep in reversed(list(self._replicas.values())):
            rep.disarm()
            rep.engine.stop()
