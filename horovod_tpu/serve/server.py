"""Streaming HTTP frontend for the serve engine (stdlib only).

Built on the same ``utils/httpd`` scaffolding as the telemetry plane.
Endpoints:

* ``POST /generate`` — body ``{"tokens": [int, ...],
  "max_new_tokens": N, "eos_id": optional, "temperature": optional,
  "top_p": optional, "seed": optional}`` (the sampling knobs of
  ``serve/sampling.py``; omitted = greedy). The response streams
  newline-delimited JSON (``application/x-ndjson``): one
  ``{"token": t}`` line per generated token **as the engine produces
  it** (HTTP/1.0, connection-close delimited — no chunked-encoding
  games), then a terminal ``{"done": true, "tokens": [...],
  "finish_reason": ...}`` line carrying the full generation. Invalid
  requests get 400 with the reason; an engine stopped mid-stream ends
  the stream with an ``{"error": ...}`` line.
* ``GET /healthz`` — serving liveness: queue depth, active sequences,
  KV-pool occupancy, installed weights version. Follows the telemetry
  plane's convention (200 ok / 503 when the engine is down) so the
  same probes drive both — and mirrors its elastic-transition shape
  with a third state: a replica refusing admission (preempt-drain or
  weight staging) answers 503 with ``status: "draining"``, which is
  what tells a fleet router (serve/fleet/) to dispatch elsewhere while
  in-flight streams finish.
* ``GET /metrics`` — the shared registry in Prometheus text format
  (the ``hvd_serve_*`` family plus everything else this process
  records), for deployments that don't also run the telemetry server.

Same security model as the metrics endpoint (docs/OBSERVABILITY.md):
binds loopback by default, no auth — put a real gateway in front
before exposing it.
"""

import json
import logging

from horovod_tpu.serve.engine import Request, RequestError
from horovod_tpu.serve.sampling import SamplingParams
from horovod_tpu.telemetry.registry import get_registry
from horovod_tpu.utils.httpd import HttpService, QuietHandler

logger = logging.getLogger("horovod_tpu")

MAX_BODY = 8 << 20  # a prompt is token ids, not tensors


class ServeServer(HttpService):
    """The generate frontend over one :class:`ServeEngine`. ``port=0``
    binds an ephemeral port (in ``.port`` after ``start()``)."""

    thread_name = "hvd_serve_http"

    def __init__(self, engine, addr="127.0.0.1", port=0, registry=None,
                 stream_timeout=300.0):
        super().__init__(addr=addr, port=port)
        self.engine = engine
        # default to the registry the ENGINE records into (an isolated
        # registry in tests, the process default in production) so
        # /metrics always shows this server's own hvd_serve_* family
        if registry is None:
            registry = getattr(getattr(engine, "instruments", None),
                               "registry", None)
        self.registry = registry if registry is not None else get_registry()
        self._stream_timeout = float(stream_timeout)

    def _handler_class(self):
        server = self

        class Handler(QuietHandler):
            log_name = "serve"

            def do_GET(self):
                try:
                    if self.path == "/healthz":
                        eng = server.engine
                        down = (eng._stop.is_set()
                                or eng._broken is not None)
                        draining = (not down
                                    and getattr(eng, "draining", False))
                        status = ("down" if down
                                  else "draining" if draining else "ok")
                        body = {
                            "status": status,
                            "queue_depth": eng.queue_depth,
                            "active": eng.active_count,
                            "kv_blocks_in_use": eng.allocator.in_use,
                            "kv_blocks_free": eng.allocator.available,
                            "weights_version": eng.weights_version,
                        }
                        # draining is 503 like down: probes pull the
                        # replica from rotation while it finishes
                        # in-flight work (admission is refused anyway)
                        self._respond_json(200 if status == "ok" else 503,
                                           body)
                    elif self.path == "/metrics":
                        self._respond(
                            200, server.registry.render_prometheus(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    else:
                        self._respond(404, "not found\n", "text/plain")
                except BrokenPipeError:
                    pass
                # hvd-lint: disable=HVD-EXCEPT -- keep the plane up; the handler reports 500 below
                except Exception as e:
                    logger.warning("serve endpoint %s failed: %s",
                                   self.path, e)
                    try:
                        self._respond(500, f"{e}\n", "text/plain")
                    # hvd-lint: disable=HVD-EXCEPT -- the client is gone; nothing left to report to
                    except Exception:
                        pass

            def do_POST(self):
                if self.path != "/generate":
                    return self._respond(404, "not found\n", "text/plain")
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    if length <= 0 or length > MAX_BODY:
                        return self._respond_json(
                            400, {"error": "body required (JSON, "
                                           f"<= {MAX_BODY} bytes)"})
                    try:
                        body = json.loads(self.rfile.read(length))
                        tokens = body["tokens"]
                        if (not isinstance(tokens, list)
                                or not all(isinstance(t, int)
                                           for t in tokens)):
                            raise ValueError(
                                "tokens must be a list of ints")
                        # Request()/SamplingParams() coerce and
                        # validate their fields — a non-numeric field
                        # is a CLIENT error, so both must be built
                        # inside this block to 400, not fall through
                        # to the generic 500 handler
                        sp = None
                        if any(k in body for k in ("temperature",
                                                   "top_p", "seed")):
                            sp = SamplingParams(
                                temperature=float(
                                    body.get("temperature", 0.0)),
                                top_p=float(body.get("top_p", 1.0)),
                                seed=int(body.get("seed", 0)))
                        req = Request(tokens,
                                      int(body.get("max_new_tokens", 16)),
                                      eos_id=body.get("eos_id"),
                                      sampling=sp,
                                      trace=bool(body.get("trace",
                                                          False)))
                    except (KeyError, ValueError, TypeError) as e:
                        return self._respond_json(400, {"error": str(e)})
                    try:
                        server.engine.submit(req)
                    except RequestError as e:
                        return self._respond_json(400, {"error": str(e)})
                    self._stream(req)
                except BrokenPipeError:
                    pass  # client went away mid-stream; engine finishes
                # hvd-lint: disable=HVD-EXCEPT -- keep the plane up; the handler reports 500 below
                except Exception as e:
                    logger.warning("serve /generate failed: %s", e)
                    try:
                        self._respond(500, f"{e}\n", "text/plain")
                    # hvd-lint: disable=HVD-EXCEPT -- the client is gone; nothing left to report to
                    except Exception:
                        pass

            def _stream(self, req):
                # HTTP/1.0 + Connection: close — the closed socket
                # delimits the ndjson stream; each token line is
                # flushed as the engine emits it
                self.send_response(200)
                self.send_header("Content-Type", "application/x-ndjson")
                self.send_header("Connection", "close")
                self.end_headers()

                def line(obj):
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()

                tr = req.trace
                first = tr is not None
                try:
                    for tok in req.stream(
                            timeout=server._stream_timeout):
                        if first:
                            # best-effort first-byte span: the trace
                            # may already be finalized for a short
                            # generation the engine finished first
                            first = False
                            t0 = tr.now()
                            line({"token": tok})
                            tr.span("stream", t0, tr.now(),
                                    actor="http", first_byte=True)
                        else:
                            line({"token": tok})
                    line({"done": True, "tokens": req.generated,
                          "finish_reason": req.finish_reason})
                except (RequestError, TimeoutError) as e:
                    line({"error": str(e)})

        return Handler

    def start(self):
        port = super().start()
        logger.info("serve endpoint on http://%s:%d/generate",
                    self._addr, port)
        return port
