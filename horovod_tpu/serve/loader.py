"""Serve-side weight loading: ckpt manifest → inference mesh.

A training checkpoint is a sharded ``TrainState`` (params, optimizer
state, batch stats, step — ``ckpt/sharded.py``). Serving needs exactly
one slice of it: the params. Two properties of the checkpoint layout
make that slice cheap and world-independent:

* ``TrainState.tree_flatten`` puts ``params`` FIRST, and replicated
  leaves are round-robin-assigned by flat leaf index — so the params
  occupy flat indices ``0..n_params-1`` regardless of what optimizer
  trained them. The loader never has to reconstruct (or even know) the
  optimizer's state tree; ZeRO bucket rows are simply never assembled.
* shard assembly is already world-independent: an N-host training
  checkpoint loads into an M-device inference mesh by reading the N
  shards' round-robin homes — the PR 9 reshard-on-load story, params
  edition.

:class:`ReloadWatcher` is the rolling-reload half: it polls the
checkpoint root with the stat-only ``manifest.latest_manifest`` probe
(no shard is opened until a NEW complete manifest appears), loads the
params, and stages them into the engine — which swaps between scheduler
iterations, dropping no in-flight request (docs/SERVING.md).
"""

import logging
import threading

import jax
import numpy as np

from horovod_tpu.ckpt import manifest as manifest_lib
from horovod_tpu.ckpt import sharded as sharded_lib

logger = logging.getLogger("horovod_tpu")


def abstract_params(model, sample_tokens=None, seq_len=8):
    """Shape-only params tree of ``model`` (flax) via ``jax.eval_shape``
    — the restore target :func:`load_params` slices a checkpoint
    against, built without materializing a single weight."""
    import jax.numpy as jnp

    if sample_tokens is None:
        sample_tokens = jnp.zeros((1, int(seq_len)), jnp.int32)
    shapes = jax.eval_shape(
        lambda rng: model.init(rng, sample_tokens),
        jax.random.PRNGKey(0))
    return shapes["params"]


def _assemble(root, step, target_leaves, treedef):
    man = manifest_lib.read_manifest(root, step)
    src_world = int(man["world"])
    shards = man.get("shards") or {}
    n = len(target_leaves)
    # params are the tree PREFIX: leaf i lives in shard i % src_world —
    # only those shards are read (each CRC-checked against the manifest)
    needed = sorted({i % src_world for i in range(n)})
    payloads = {r: sharded_lib._read_shard(root, step, r, src_world,
                                           shards.get(str(r)))
                for r in needed}
    out = []
    for i, leaf in enumerate(target_leaves):
        try:
            saved = payloads[i % src_world]["repl"][str(i)]
        except KeyError:
            raise ValueError(
                f"checkpoint step {step} has no replicated leaf {i} of "
                f"{n} — the params-prefix contract expects a TrainState "
                "checkpoint (ckpt/sharded.py) whose params tree matches "
                "the serving model") from None
        saved = np.asarray(saved)
        if saved.shape != tuple(leaf.shape):
            # msgpack round-trips 0-d arrays as shape (1,); any
            # same-size difference is a benign layout artifact
            if saved.size == int(np.prod(leaf.shape, dtype=np.int64)):
                saved = saved.reshape(leaf.shape)
            else:
                raise ValueError(
                    f"checkpoint params leaf {i} has shape "
                    f"{saved.shape}, the serving model expects "
                    f"{tuple(leaf.shape)} — wrong model config for this "
                    "checkpoint")
        if saved.dtype != leaf.dtype:
            saved = saved.astype(leaf.dtype)
        out.append(saved)
    return jax.tree_util.tree_unflatten(treedef, out), \
        man.get("meta") or {}


def load_params(root, params_target, step=None):
    """Load ONLY the parameter tree of a sharded checkpoint.

    ``params_target`` is a shape/dtype tree (:func:`abstract_params`,
    or a live tree). ``step=None`` picks the newest manifest-complete
    step, falling back past steps whose shards fail validation —
    restore-side torn-write philosophy, same as
    ``ckpt.restore_sharded``; an explicit ``step`` fails loudly.
    Returns ``(step, params, meta)`` with host-numpy leaves cast to the
    target dtypes (a bf16 serving config loads an fp32 checkpoint
    narrowed; same-dtype loads are bitwise)."""
    leaves, treedef = jax.tree_util.tree_flatten(params_target)
    if step is not None:
        if not manifest_lib.is_complete(root, step):
            raise FileNotFoundError(
                f"step {step} under {root} has no "
                f"{manifest_lib.MANIFEST_NAME} (incomplete/torn "
                "checkpoint)")
        params, meta = _assemble(root, step, leaves, treedef)
        return step, params, meta
    steps = manifest_lib.list_complete_steps(root)
    if not steps:
        raise FileNotFoundError(
            f"no manifest-complete checkpoint under {root}")
    last_err = None
    for s in reversed(steps):
        try:
            params, meta = _assemble(root, s, leaves, treedef)
            return s, params, meta
        except (OSError, sharded_lib.ShardValidationError) as e:
            logger.warning(
                "serve: ckpt step %d under %s is unloadable (%s) — "
                "falling back to the previous complete step", s, root, e)
            last_err = e
    raise ValueError(
        f"no loadable checkpoint under {root}: all {len(steps)} "
        "manifest-complete step(s) failed validation") from last_err


class ReloadWatcher:
    """Rolling weight reload: poll ``root`` for a newer complete
    manifest, load its params, stage them into the engine.

    The poll is the stat-only :func:`ckpt.manifest.complete_manifests`
    probe. Candidates are ranked by **manifest mtime**, not step
    number: recency by commit time is what survives the documented
    backwards-step-numbering case — a damaged highest-numbered step
    forces training's fallback restore, after which fresh commits carry
    LOWER step numbers (with newer mtimes). Ranking by step would pin
    the watcher on the damaged step forever and blind it to every fresh
    commit beneath it. The ``(step, mtime)`` key also catches a
    re-commit of the same step number. A probe whose shards fail
    validation is remembered (and dropped once its dir is GC'd) and not
    retried; the engine keeps serving the weights it has. Swap
    semantics live in ``ServeEngine.install_weights``: between
    iterations, in-flight requests carried over."""

    def __init__(self, root, engine, params_target, poll_s=2.0,
                 on_reload=None):
        self._root = root
        self._engine = engine
        self._target = params_target
        self._poll_s = float(poll_s)
        self._on_reload = on_reload
        self._seen = None    # (step, mtime) last installed
        self._bad = set()    # (step, mtime) probes that failed to load
        self._stop = threading.Event()
        self._thread = None

    def poll_once(self):
        """One probe+maybe-reload cycle; returns the newly installed
        step or None. Synchronous — the deterministic test surface."""
        probes = manifest_lib.complete_manifests(self._root)
        self._bad &= set(probes)  # GC'd/re-committed dirs drop out
        candidates = [p for p in probes if p not in self._bad]
        if not candidates:
            return None
        probe = max(candidates, key=lambda p: (p[1], p[0]))
        if probe == self._seen:
            return None
        step = probe[0]
        try:
            loaded_step, params, _ = load_params(self._root,
                                                 self._target, step=step)
        # hvd-lint: disable=HVD-EXCEPT -- bad ckpt is remembered+skipped; current weights keep serving
        except Exception as e:
            logger.warning(
                "serve: reload of ckpt step %d failed (%s) — keeping "
                "the current weights", step, e)
            self._bad.add(probe)
            return None
        self._engine.install_weights(params, version=loaded_step)
        self._seen = probe
        logger.info("serve: staged reloaded weights from ckpt step %d",
                    loaded_step)
        if self._on_reload is not None:
            self._on_reload(loaded_step)
        return loaded_step

    def mark_current(self, step):
        """Record the step already installed at startup so the first
        poll doesn't re-load it."""
        mt = manifest_lib.manifest_mtime(self._root, step)
        if mt is not None:
            self._seen = (step, mt)

    def _loop(self):
        while not self._stop.wait(self._poll_s):
            try:
                self.poll_once()
            # hvd-lint: disable=HVD-EXCEPT -- keep watching; serving must not die
            except Exception:  # keep watching; serving must not die
                logger.warning("serve: reload poll failed",
                               exc_info=True)

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            name="hvd_serve_reload",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
