"""Handle-based collective ops on torch tensors.

Rebuilds ``horovod/torch/mpi_ops.py`` (allreduce_async/_, allgather_async,
broadcast_async/_, alltoall, poll, synchronize) over the native core
(``horovod_tpu._core`` — the TCP ring data plane; reference role:
``mpi_ops_v2.cc`` enqueueing into the C++ background thread). Tensors are
host CPU tensors here — TPU-resident training uses the JAX path.

Async semantics match the reference: ``*_async`` returns a handle
immediately, the background thread negotiates + executes, ``synchronize``
blocks and produces the result. In-place variants write back into the
input tensor.
"""

import numpy as np
import torch

from horovod_tpu import _core
from horovod_tpu.ops.reduction import Adasum, Average, Max, Min, Sum

_name_counter = {}


def _ensure_core():
    """The torch ops need the native core. Multi-process jobs start it in
    ``hvd.init()`` (launcher env contract); single-process gets a local
    size-1 core on first use. Calling without ``init()`` raises, like the
    reference (``check_initialized``)."""
    from horovod_tpu import basics
    if not basics.is_initialized():
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init()")
    if not _core.is_initialized():
        _core.init(rank=0, size=1)


def _auto_name(kind, name):
    if name is not None:
        return name
    n = _name_counter.get(kind, 0)
    _name_counter[kind] = n + 1
    return f"{kind}.noname.{n}"


def _grad_name(name):
    """Backward-collective name derived from the forward op's name, so a
    cross-rank mismatch stalls on one named tensor (None falls back to
    auto-numbering — only reachable via direct .apply with name=None)."""
    return f"{name}.grad" if name is not None else None


class TorchHandle:
    """Wraps a core handle; optionally writes the result back in place.

    ``inplace_tensor`` marks the zero-copy path: the core borrowed the
    tensor's own memory, so after ``wait`` the result already sits in it
    and no copy-back is needed."""

    def __init__(self, core_handle, out_tensor=None, postprocess=None,
                 inplace_tensor=None):
        self._h = core_handle
        self._out = out_tensor
        self._post = postprocess
        self._inplace = inplace_tensor

    def poll(self):
        return self._h.poll()

    def synchronize(self):
        arr = self._h.wait()
        if self._inplace is not None:
            return self._inplace
        t = torch.from_numpy(np.array(arr))
        if self._post is not None:
            t = self._post(t)
        if self._out is not None:
            if self._out.shape != t.shape:
                self._out.resize_(t.shape)
            self._out.copy_(t)
            return self._out
        return t


def _to_numpy(tensor):
    _ensure_core()
    if tensor.device.type != "cpu":
        raise ValueError(
            "the torch adapter operates on CPU tensors; TPU-resident "
            "training uses the JAX path (horovod_tpu.ops.collective)")
    return tensor.detach().contiguous().numpy()


def allreduce_async(tensor, average=True, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0):
    op = op or (Average if average else Sum)
    h = _core.allreduce_async(_to_numpy(tensor), _auto_name("allreduce",
                                                            name),
                              op=op, prescale=prescale_factor,
                              postscale=postscale_factor)
    return TorchHandle(h)


# ---- differentiable collectives (reference torch/mpi_ops.py:158-385:
# the Horovod* autograd Functions let users backprop THROUGH an
# hvd op, not just reduce gradients) --------------------------------------

class HorovodAllreduce(torch.autograd.Function):
    """d(allreduce)/dx is another allreduce with the same op and scale
    factors — both are linear multipliers, so the transpose reuses them
    (reference mpi_ops.py:158-170)."""

    @staticmethod
    def forward(ctx, tensor, average, name, op, prescale, postscale):
        ctx.average = average
        ctx.op = op
        ctx.name = name
        ctx.prescale = prescale
        ctx.postscale = postscale
        return allreduce_async(tensor, average, name, op,
                               prescale_factor=prescale,
                               postscale_factor=postscale).synchronize()

    @staticmethod
    def backward(ctx, grad_output):
        grad = HorovodAllreduce.apply(grad_output, ctx.average,
                                      _grad_name(ctx.name), ctx.op,
                                      ctx.prescale, ctx.postscale)
        return grad, None, None, None, None, None


class HorovodAllgather(torch.autograd.Function):
    """Backward sums the cotangent across ranks, then each rank slices
    out the rows it contributed (reference mpi_ops.py:289-310). Per-rank
    row counts are gathered once in FORWARD (which already pays a
    synchronization) and stashed, so backward adds no extra collective
    round-trip for them.

    All auxiliary collectives are named after the main op
    (``{name}.dims`` / ``{name}.grad``) rather than auto-numbered, so if
    ``requires_grad`` differs across ranks for the same logical call the
    mismatch shows up as a stall on one named tensor that the stall
    inspector can report — ``requires_grad`` must agree across ranks."""

    @staticmethod
    def forward(ctx, tensor, name):
        ctx.name = name
        dname = f"{name}.dims" if name is not None else None
        ctx.dims = allgather_async(
            torch.tensor([tensor.shape[0]]),
            name=dname).synchronize().tolist()
        return allgather_async(tensor, name).synchronize()

    @staticmethod
    def backward(ctx, grad_output):
        grad_reduced = allreduce_async(
            grad_output, average=False,
            name=_grad_name(ctx.name)).synchronize()
        r = _core.rank()
        start = int(sum(ctx.dims[:r]))
        return grad_reduced[start:start + ctx.dims[r]], None


class HorovodBroadcast(torch.autograd.Function):
    """Backward sums cotangents onto the root; non-roots contribute
    their gradient but receive zero (reference mpi_ops.py:371-385)."""

    @staticmethod
    def forward(ctx, tensor, root_rank, name):
        ctx.root_rank = root_rank
        ctx.name = name
        return broadcast_async(tensor, root_rank, name).synchronize()

    @staticmethod
    def backward(ctx, grad_output):
        grad_reduced = allreduce_async(
            grad_output, average=False,
            name=_grad_name(ctx.name)).synchronize()
        if _core.rank() != ctx.root_rank:
            grad_reduced = grad_reduced * 0
        return grad_reduced, None, None


def allreduce(tensor, average=True, name=None, op=None, compression=None,
              prescale_factor=1.0, postscale_factor=1.0):
    from horovod_tpu.torch.compression import Compression
    compression = compression or Compression.none
    wire, ctx = compression.compress(tensor)
    if wire.requires_grad:
        out = HorovodAllreduce.apply(wire, average,
                                     _auto_name("allreduce", name),
                                     op or (Average if average else Sum),
                                     prescale_factor, postscale_factor)
    else:
        out = allreduce_async(wire, average=average, name=name, op=op,
                              prescale_factor=prescale_factor,
                              postscale_factor=postscale_factor
                              ).synchronize()
    return compression.decompress(out, ctx)


def allreduce_async_(tensor, average=True, name=None, op=None, **kw):
    """In-place: the result is written back into ``tensor``. Contiguous
    CPU tensors take the zero-copy path — the core reduces directly in
    the tensor's memory (reference wraps framework tensors the same way,
    common.h:188-223)."""
    op = op or (Average if average else Sum)
    if tensor.device.type == "cpu" and tensor.is_contiguous():
        _ensure_core()
        h = _core.allreduce_async(tensor.detach().numpy(),  # shares memory
                                  _auto_name("allreduce", name), op=op,
                                  inplace=True, **kw)
        return TorchHandle(h, inplace_tensor=tensor)
    h = _core.allreduce_async(_to_numpy(tensor),
                              _auto_name("allreduce", name), op=op, **kw)
    return TorchHandle(h, out_tensor=tensor)


def allreduce_(tensor, average=True, name=None, op=None, **kw):
    return allreduce_async_(tensor, average=average, name=name, op=op,
                            **kw).synchronize()


def allgather_async(tensor, name=None):
    h = _core.allgather_async(_to_numpy(tensor),
                              _auto_name("allgather", name))
    return TorchHandle(h)


def allgather(tensor, name=None):
    if tensor.requires_grad:
        return HorovodAllgather.apply(tensor, _auto_name("allgather", name))
    return allgather_async(tensor, name).synchronize()


def broadcast_async(tensor, root_rank, name=None):
    h = _core.broadcast_async(_to_numpy(tensor),
                              _auto_name("broadcast", name),
                              root_rank=root_rank)
    return TorchHandle(h)


def broadcast(tensor, root_rank, name=None):
    if tensor.requires_grad:
        return HorovodBroadcast.apply(tensor, root_rank,
                                      _auto_name("broadcast", name))
    return broadcast_async(tensor, root_rank, name).synchronize()


def broadcast_async_(tensor, root_rank, name=None):
    """In-place broadcast; contiguous CPU tensors go zero-copy, which is
    what makes ``broadcast_parameters`` on a large model copy nothing."""
    if tensor.device.type == "cpu" and tensor.is_contiguous():
        _ensure_core()
        h = _core.broadcast_async(tensor.detach().numpy(),  # shares memory
                                  _auto_name("broadcast", name),
                                  root_rank=root_rank, inplace=True)
        return TorchHandle(h, inplace_tensor=tensor)
    h = _core.broadcast_async(_to_numpy(tensor),
                              _auto_name("broadcast", name),
                              root_rank=root_rank)
    return TorchHandle(h, out_tensor=tensor)


def broadcast_(tensor, root_rank, name=None):
    return broadcast_async_(tensor, root_rank, name).synchronize()


def alltoall(tensor, name=None):
    h = _core.alltoall_async(_to_numpy(tensor), _auto_name("alltoall",
                                                           name))
    return TorchHandle(h).synchronize()


def join(device=-1):
    """Announce data exhaustion; blocks until every rank joined and
    returns the rank that joined LAST (reference torch/mpi_ops.py:494;
    `device` kept for signature parity — there are no per-device zero
    buffers to stage on the host plane)."""
    del device
    _ensure_core()
    return _core.join()


def poll(handle):
    return handle.poll()


def synchronize(handle):
    return handle.synchronize()


def broadcast_object(obj, root_rank=0, name=None):
    """Broadcast an arbitrary picklable object (two-phase: length then
    padded payload — shapes must agree across ranks)."""
    import pickle
    name = _auto_name("bcast_object", name)
    payload = pickle.dumps(obj)
    n = torch.tensor([len(payload)], dtype=torch.int64)
    n = broadcast(n, root_rank, name=f"{name}.len")
    buf = torch.zeros(int(n.item()), dtype=torch.uint8)
    if len(payload) == int(n.item()):
        buf[:] = torch.from_numpy(
            np.frombuffer(payload, dtype=np.uint8).copy())
    buf = broadcast(buf, root_rank, name=f"{name}.data")
    return pickle.loads(buf.numpy().tobytes())
