"""PyTorch adapter (reference: ``horovod/torch/__init__.py``).

The full Horovod torch contract — ``hvd.init()``, ``DistributedOptimizer``
hooking gradient-ready events to async allreduces, ``broadcast_parameters``
/ ``broadcast_optimizer_state`` at startup — over the native core's TCP
ring data plane. CPU-tensor path (this image ships torch-cpu); TPU
training belongs to the JAX path.
"""

import torch

from horovod_tpu.basics import (cross_rank, cross_size, init,
                                is_initialized, local_rank, local_size,
                                mpi_threads_supported, rank, shutdown, size)
from horovod_tpu.torch.compression import Compression
from horovod_tpu.torch.mpi_ops import (Adasum, Average, Max, Min, Sum,
                                       allgather, allgather_async,
                                       allreduce, allreduce_,
                                       allreduce_async, allreduce_async_,
                                       alltoall, broadcast, broadcast_,
                                       broadcast_async, broadcast_async_,
                                       broadcast_object, join, poll,
                                       synchronize)

__all__ = [
    "init", "shutdown", "is_initialized", "rank", "size", "local_rank",
    "local_size", "cross_rank", "cross_size", "mpi_threads_supported",
    "Sum", "Average", "Adasum", "Min", "Max", "Compression",
    "allreduce", "allreduce_", "allreduce_async", "allreduce_async_",
    "allgather", "allgather_async", "broadcast", "broadcast_",
    "broadcast_async", "broadcast_async_", "broadcast_object", "alltoall",
    "join", "poll", "synchronize", "DistributedOptimizer",
    "broadcast_parameters", "broadcast_optimizer_state",
]


class _DistributedOptimizer(torch.optim.Optimizer):
    """Wraps a torch optimizer: gradient-ready hooks fire async allreduces,
    ``step()`` synchronizes them all, then runs the inner step (reference
    ``horovod/torch/__init__.py:57-212``).

    ``backward_passes_per_step=N`` follows the reference contract: grads
    accumulate locally over N backwards and the allreduce averages the
    accumulated SUM across ranks — no division by N (scale the learning
    rate if you want a micro-batch mean). Note the JAX adapter's
    ``optax.MultiSteps`` path averages over micro-steps instead.
    """

    def __init__(self, optimizer, named_parameters=None, compression=None,
                 backward_passes_per_step=1, op=Average):
        self._inner = optimizer
        self._compression = compression or Compression.none
        self._passes = backward_passes_per_step
        self._op = op
        self._handles = {}
        self._hook_registered = []

        if named_parameters is not None:
            named = list(named_parameters)
        else:
            named = []
            for gi, group in enumerate(optimizer.param_groups):
                for pi, p in enumerate(group["params"]):
                    named.append((f"allreduce.noname.{gi}.{pi}", p))
        dups = {n for n in [x for x, _ in named]
                if [x for x, _ in named].count(n) > 1}
        if dups:
            raise ValueError(f"duplicate parameter names: {sorted(dups)}")
        self._named = named
        self._name_of = {p: n for n, p in named}
        self._requires_update = {p for _, p in named if p.requires_grad}
        # per-param countdown: the hook fires the allreduce on the Nth
        # backward (reference torch/__init__.py:118-135 _allreduce_delay)
        self._delay = {p: self._passes for p in self._requires_update}
        self._register_hooks()

    # -- torch.optim.Optimizer surface delegates to the inner optimizer --
    @property
    def param_groups(self):
        return self._inner.param_groups

    @property
    def state(self):
        return self._inner.state

    def state_dict(self):
        return self._inner.state_dict()

    def load_state_dict(self, sd):
        return self._inner.load_state_dict(sd)

    def zero_grad(self, set_to_none=True):
        if self._handles:
            raise AssertionError(
                "optimizer.zero_grad() was called after loss.backward() but "
                "before optimizer.step() or optimizer.synchronize(); this "
                "would discard gradients with allreduces still in flight")
        return self._inner.zero_grad(set_to_none=set_to_none)

    def _register_hooks(self):
        for name, p in self._named:
            if not p.requires_grad:
                continue
            self._hook_registered.append(
                p.register_post_accumulate_grad_hook(self._make_hook(name)))

    def _fire_allreduce(self, p):
        wire, ctx = self._compression.compress(p.grad)
        from horovod_tpu.torch import mpi_ops
        h = mpi_ops.allreduce_async(wire, name=self._name_of[p], op=self._op)
        return h, ctx

    def _make_hook(self, name):
        def hook(p):
            if p in self._handles and self._handles[p][0] is not None:
                raise AssertionError(
                    f"gradient for {name!r} was computed more than "
                    f"backward_passes_per_step={self._passes} times before "
                    "step()/synchronize(); call synchronize() between "
                    "extra backward passes")
            self._delay[p] -= 1
            handle, ctx = None, None
            if self._delay[p] == 0:
                handle, ctx = self._fire_allreduce(p)
            self._handles[p] = (handle, ctx)
        return hook

    def synchronize(self):
        # params whose countdown has not elapsed, or whose hook never
        # fired this step, are allreduced now so step() never consumes
        # unreduced gradients (reference torch/__init__.py:155-173)
        for p, (h, ctx) in list(self._handles.items()):
            if h is None:
                self._handles[p] = self._fire_allreduce(p)
        for p in self._requires_update - set(self._handles):
            if p.grad is not None:
                self._handles[p] = self._fire_allreduce(p)
        for p, (h, ctx) in self._handles.items():
            out = h.synchronize()
            self._delay[p] = self._passes
            p.grad.copy_(self._compression.decompress(out, ctx))
        self._handles.clear()

    def step(self, closure=None):
        # Always synchronize and run the inner step, like the reference:
        # gradient accumulation is expressed by the per-param delay
        # counters, not by skipping optimizer steps.
        self.synchronize()
        return self._inner.step(closure)


def DistributedOptimizer(optimizer, named_parameters=None, compression=None,
                         backward_passes_per_step=1, op=Average):
    return _DistributedOptimizer(
        optimizer, named_parameters=named_parameters,
        compression=compression,
        backward_passes_per_step=backward_passes_per_step, op=op)


def broadcast_parameters(params, root_rank=0):
    """Sync model state from root at startup (reference
    ``torch/__init__.py:440-470``). Accepts a ``state_dict()`` or an
    iterable of ``(name, tensor)``."""
    if isinstance(params, dict):
        items = sorted(params.items())
    else:
        items = list(params)
    handles = []
    from horovod_tpu.torch import mpi_ops
    for name, t in items:
        if not torch.is_tensor(t):
            continue
        handles.append(mpi_ops.broadcast_async_(t.data, root_rank,
                                                name=f"bp.{name}"))
    for h in handles:
        h.synchronize()


def broadcast_optimizer_state(optimizer, root_rank=0):
    """Broadcast optimizer state dict from root
    (``torch/__init__.py:472-560``): tensors ride the data plane,
    non-tensor scalars ride broadcast_object."""
    if isinstance(optimizer, _DistributedOptimizer):
        optimizer = optimizer._inner
    sd = optimizer.state_dict()
    # Root drives the whole broadcast set: non-root ranks may have EMPTY
    # state (fresh process restoring from a rank-0 checkpoint), so the
    # list of (pid, key, shape, dtype) comes from root and missing
    # tensors are materialized locally before the tensor broadcasts —
    # otherwise ranks would enqueue mismatched sets and negotiation
    # would stall (reference torch/__init__.py:472-560 initializes
    # state on all ranks before broadcasting).
    meta = {
        "param_groups": sd["param_groups"],
        "scalars": {
            (pid, k): v
            for pid, st in sd["state"].items() for k, v in st.items()
            if not torch.is_tensor(v)
        },
        "tensors": [
            (pid, k, list(v.shape), str(v.dtype))
            for pid, st in sd["state"].items() for k, v in st.items()
            if torch.is_tensor(v)
        ],
    }
    meta = broadcast_object(meta, root_rank, name="bos.meta")
    sd["param_groups"] = meta["param_groups"]
    # Root's state set is authoritative: local entries root does not have
    # (e.g. this rank warmed momentum root never had) must not survive,
    # or ranks would step with divergent state after the "sync".
    root_keys = ({(pid, k) for (pid, k) in meta["scalars"]} |
                 {(pid, k) for pid, k, _, _ in meta["tensors"]})
    for pid, st in list(sd["state"].items()):
        for k in list(st):
            if (pid, k) not in root_keys:
                del st[k]
        if not st:
            del sd["state"][pid]
    for (pid, k), v in meta["scalars"].items():
        sd["state"].setdefault(pid, {})[k] = v
    tensors = []
    for pid, k, shape, dtype_s in meta["tensors"]:
        st = sd["state"].setdefault(pid, {})
        t = st.get(k)
        dtype = getattr(torch, dtype_s.replace("torch.", ""))
        if (not torch.is_tensor(t) or list(t.shape) != shape
                or t.dtype != dtype):
            t = torch.zeros(shape, dtype=dtype)
            st[k] = t
        tensors.append((f"bos.{pid}.{k}", t))
    broadcast_parameters(tensors, root_rank)
    optimizer.load_state_dict(sd)
