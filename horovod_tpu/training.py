"""SPMD training-step builders: the Horovod programming model, compiled.

The reference's user contract is "compute local gradients, the framework
averages them" (``horovod/torch/__init__.py:57`` et al.). Here that contract
is compiled into one XLA program: ``make_train_step`` wraps a flax model +
``DistributedOptimizer`` into a ``shard_map``-ped step over the global mesh
— per-shard batches in, replicated params/optimizer state, gradient
allreduce (fused/hierarchical/compressed) inside.

These builders power ``bench.py``, ``__graft_entry__.py``, ``examples/``
and the end-to-end tests; they are also the reference pattern for users
writing their own steps.
"""

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops import collective
from horovod_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class TrainState:
    """Replicated training state (params + optimizer + BN stats + step)."""
    params: Any
    opt_state: Any
    batch_stats: Any
    step: Any

    def tree_flatten(self):
        return ((self.params, self.opt_state, self.batch_stats, self.step),
                None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def softmax_cross_entropy(logits, labels):
    """Mean cross-entropy with integer labels."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return -jnp.mean(ll)


def create_train_state(model, tx, rng, sample_input, **apply_kwargs):
    """Initialize replicated state for ``model`` (flax) and optimizer ``tx``
    (typically ``hvd.DistributedOptimizer(optax...)``)."""
    variables = model.init(rng, sample_input, train=False, **apply_kwargs)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    return TrainState(params=params, opt_state=tx.init(params),
                      batch_stats=batch_stats, step=jnp.zeros((), jnp.int32))


def replicated_specs(state):
    return jax.tree_util.tree_map(lambda _: P(), state)


def state_specs(state):
    """PartitionSpecs for a :class:`TrainState`: everything replicated,
    except ZeRO-sharded optimizer state (``parallel/zero.ZeroState``) whose
    bucket rows are sharded over their scatter axes — the ~1/N
    optimizer-state memory is real, not just an algorithmic claim.
    Delegates to ``parallel/gspmd.state_partition_specs`` — ONE spec
    authority, shared by the explicit shard_map path, the GSPMD jit
    path, placement and checkpointing."""
    from horovod_tpu.parallel import gspmd as gspmd_lib

    return gspmd_lib.state_partition_specs(state)


def _put(x, sharding):
    """``device_put`` to ``sharding``, multi-process safe: host or
    process-local values headed for a sharding that spans processes are
    sliced locally (``cluster.procmesh.place``) instead of letting
    device_put broadcast the whole value through the collective fabric
    to assert cross-process equality — that broadcast runs per call,
    per leaf, and on the gloo CPU transport it can mis-pair with the
    step's own async collectives. Already-global arrays keep the plain
    device_put (no-op when already placed)."""
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    from horovod_tpu.cluster import procmesh

    return procmesh.place(x, sharding)


def _placer(mesh, spec):
    """device_put to a stable NamedSharding (no-op when already placed).

    ``spec`` is a single PartitionSpec for every leaf, or a pytree of
    specs matching the data (the ZeRO state path). Keeping input shardings
    identical across calls matters: the first call sees uncommitted host
    arrays while later calls see outputs committed to the mesh — without
    pinning, jit recompiles and (on jax 0.9 CPU meshes) trips an XLA
    buffer-count mismatch."""
    if isinstance(spec, P):
        sharding = jax.sharding.NamedSharding(mesh, spec)

        def place(tree):
            return jax.tree_util.tree_map(
                lambda x: _put(x, sharding), tree)

        return place

    def place(tree):
        return jax.tree_util.tree_map(
            lambda x, s: _put(
                x, jax.sharding.NamedSharding(mesh, s)), tree, spec)

    return place


def make_train_step(model, tx, mesh=None, loss_fn=softmax_cross_entropy,
                    batch_axes=None, donate=True, dropout_seed=0,
                    accum_steps=1, overlap_grads=False, telemetry=None,
                    error_feedback=True, loader=None, spmd=False):
    """Build a jitted SPMD classification train step.

    ``spmd=True`` selects the **GSPMD hot path** (docs/PERFORMANCE.md,
    "The GSPMD path"): the whole step is jitted with
    ``in_shardings``/``out_shardings`` derived from one
    :class:`~horovod_tpu.parallel.gspmd.GspmdPlan` — batches sharded
    over the data axes, params replicated, ZeRO-1 rows ``P(data)`` —
    and contains **no explicit collective calls**; XLA inserts the
    gradient reduction (and, for ``sharded_update``, the
    reduce-scatter/all-gather pair) from the sharding annotations, and
    the latency-hiding scheduler overlaps them with compute. Same
    ``step(state, inputs, labels)`` contract and interchangeable
    optimizer state/checkpoints. Semantics differences, documented:
    BatchNorm normalizes with GLOBAL-batch statistics (sync-BN; the
    explicit path is per-shard), and dropout draws one global stream.
    ``accum_steps``/``overlap_grads`` are the explicit pipeline's knobs
    and are rejected here; a wire-compressed optimizer compiles the
    compression IN-PLACE — chunked quantizers (fp8/int8) as a
    ``shard_map`` island inside the jitted program (which restores the
    explicit path's per-shard BN/dropout semantics for that build),
    cast wires (bf16/float16) as dtype-narrowed sharding constraints
    that keep the annotation-only program — docs/PERFORMANCE.md.

    Returns ``step(state, inputs, labels) -> (state, loss)`` where
    ``inputs``/``labels`` are global arrays whose leading (batch) dim is
    sharded over the data axes and ``state`` is replicated (ZeRO-sharded
    optimizer state excepted).

    ``loader`` (a ``horovod_tpu.data.PrefetchLoader``) wires the data
    plane in: the step's own mesh placement (``device_put`` to the data
    axes) is installed into the loader, so batches are staged onto
    device BY THE PREFETCH THREAD while the previous step runs, and
    ``step(state)`` with no batch arguments pulls ``(inputs, labels)``
    from the loader (recording ``hvd_data_wait_seconds`` for any stall).
    The loader only changes who feeds the program, never the program:
    the compiled step is byte-identical with and without one
    (tests/test_data_plane.py). Gradients are allreduced by ``tx`` (wrap
    with ``hvd.DistributedOptimizer``); BN stats are averaged across shards
    (per-shard normalization like the reference, one consistent stats copy
    for checkpointing); loss is averaged.

    ``accum_steps=K`` splits each shard's batch into K equal microbatches
    and accumulates gradients across them (one optimizer step per call —
    the compiled analogue of ``backward_passes_per_step``, with the batch
    presented whole). With ``overlap_grads=True`` the exchange is the
    bucketed reduce-scatter PIPELINE: each microbatch's gradient buckets
    (reverse-traversal order — ready-first) are reduce-scattered as soon as
    that microbatch's backward produces them, so microbatch k+1's compute
    overlaps bucket k's reduction inside one XLA program (the async-
    collective scheduler flags — ``config.xla_overlap_flags`` — make the
    overlap real on TPU). The accumulators hold 1/N-sized reduced shards
    instead of full gradients. The shards then feed either one all-gather
    per bucket + the inner optimizer (plain data parallelism) or the
    ZeRO-1 sharded update (``DistributedOptimizer(sharded_update=True)``)
    with no extra gradient all-gather at all. Numerics match the
    ``accum_steps=1`` baseline up to reduction-order tolerance when the
    model is microbatch-invariant (no BatchNorm across microbatches).
    ``overlap_grads`` requires ``tx`` to be a ``DistributedOptimizer``.

    ``telemetry`` (default: auto — on when a metrics endpoint is
    configured, see ``horovod_tpu.telemetry.enabled``) instruments the
    returned step: step latency / examples-per-sec / dispatch-time
    metrics plus deferred loss and grad-norm gauges, timeline counter
    events, and a flow linking the tracing dispatch to its bucket
    markers. When on, the compiled program additionally computes the
    gradient L2 norm (exact norm of the globally-averaged gradient in
    the overlapped paths; root-mean of per-shard local norms otherwise —
    docs/OBSERVABILITY.md); when off the program is byte-identical to
    the uninstrumented build.

    **Wire compression** (``DistributedOptimizer(compression=...)``) in
    the ``overlap_grads`` pipeline narrows every bucket collective to the
    wire format. The format is resolved when THIS function is called and
    baked into the compiled program — build the step after the autotuner
    installs ``config.wire_dtype`` (a later config change warns at the
    next step call instead of silently applying). The reduce-scatter
    ships quantized gradient rows, and
    the all-gather (of gradient shards, or of ZeRO-1's parameter deltas)
    ships quantized shards — 1/4 the wire bytes at fp8/int8, 1/2 at
    bf16. With ``error_feedback=True`` (default) one fp32 residual per
    bucket AND direction is threaded through the step: each step's
    quantization error is added back into the next step's bucket before
    encoding, which is what keeps the compressed trajectory within the
    documented epsilon of the exact one (docs/PERFORMANCE.md, "Wire
    compression"). The residual buffers live OUTSIDE the checkpointable
    ``TrainState`` — they are rebuildable state, initialized to zero and
    excluded from checkpoint manifests; a restore merely restarts the
    compensation (``step.reset_error_feedback()`` drops the carry
    explicitly after rolling ``state`` back to an earlier commit, and a
    step that raises drops it automatically — the donated buffers may
    already be invalid). With ``tx.compression is None`` the residual plumbing
    vanishes and the compiled program is byte-identical to the
    uncompressed build.
    """
    from horovod_tpu import hvd_jax
    from horovod_tpu import telemetry as telemetry_lib
    from horovod_tpu.ops import fusion
    from horovod_tpu.parallel import zero as zero_lib

    if spmd:
        return _make_spmd_train_step(
            model, tx, mesh=mesh, loss_fn=loss_fn, batch_axes=batch_axes,
            donate=donate, dropout_seed=dropout_seed,
            accum_steps=accum_steps, overlap_grads=overlap_grads,
            telemetry=telemetry, error_feedback=error_feedback,
            loader=loader)

    tele_on = (telemetry_lib.enabled() if telemetry is None
               else bool(telemetry))

    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    data_axes = batch_axes or mesh_lib.data_axis_names(mesh)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    pipelined = overlap_grads or accum_steps > 1
    is_hvd_tx = isinstance(tx, hvd_jax.HorovodOptimizer)
    if pipelined:
        if not is_hvd_tx:
            raise ValueError(
                "accum_steps>1 / overlap_grads=True need the optimizer "
                "built by hvd.DistributedOptimizer(...) — the pipeline "
                "takes over its gradient reduction")
        if tx.backward_passes_per_step > 1:
            raise ValueError(
                "accum_steps and backward_passes_per_step are two "
                "accumulators for the same thing; use accum_steps")
    sharded_tx = is_hvd_tx and tx.sharded_update
    reduce_axes = (tuple(tx.axes) if is_hvd_tx and tx.axes is not None
                   else data_axes)
    # wire compression rides the bucket collectives of the OVERLAP
    # pipeline here; the non-overlapped paths compress inside tx's own
    # fused allreduce / sharded_update. Error feedback needs a
    # step-to-step carry, so it exists only when a wire format is on.
    # The wire format is resolved HERE, once: it is baked into the
    # compiled program (bucket collectives, residual shapes), so build
    # the step AFTER the autotuner installs its wire-axis winner. A
    # config change after build cannot take effect — _check_wire_drift
    # warns instead of silently diverging from tx.compression.
    wire = tx.compression if (is_hvd_tx and overlap_grads) else None
    use_ef = wire is not None and error_feedback

    def _grad_schedule(params, world):
        """The ONE bucket-schedule recipe for this step's gradient
        exchange — local_step (world from the named axes) and the EF
        residual allocation (world from the step's mesh) must shape
        against the same plan."""
        return fusion.bucket_schedule(
            jax.tree_util.tree_leaves(params), world=world,
            threshold_bytes=tx.threshold_bytes, axes=reduce_axes,
            hierarchical=tx._hierarchical_resolved())

    _wire_drift_warned = [False]

    def _check_wire_drift():
        if not is_hvd_tx or not overlap_grads or _wire_drift_warned[0]:
            return
        now = tx.compression
        if now is not wire:
            _wire_drift_warned[0] = True
            import warnings
            warnings.warn(
                f"tx.compression resolves to "
                f"{getattr(now, 'name', None)!r} but this train step was "
                f"built with {getattr(wire, 'name', None)!r} — the wire "
                "format is baked into the compiled program at "
                "make_train_step time. Rebuild the step (after the "
                "autotuner / config install) for the new format to take "
                "effect.", stacklevel=3)

    def micro_grads(state, stats, inputs, labels, dropout_rng):
        """Loss + grads of one microbatch at fixed params."""
        def compute_loss(params):
            variables = {"params": params}
            if stats:
                variables["batch_stats"] = stats
                logits, mutated = model.apply(
                    variables, inputs, train=True, mutable=["batch_stats"],
                    rngs={"dropout": dropout_rng})
                return loss_fn(logits, labels), mutated["batch_stats"]
            logits = model.apply(variables, inputs, train=True,
                                 rngs={"dropout": dropout_rng})
            return loss_fn(logits, labels), {}

        return jax.value_and_grad(compute_loss, has_aux=True)(state.params)

    def local_step(state, wire_state, inputs, labels):
        # wire_state: {"rs": [per-bucket residual], "ag": [...]} — empty
        # (no leaves, so no effect on the compiled program) unless error
        # feedback is on. Each residual arrives as this shard's [1, n]
        # row of the [world, n] global buffer; squeeze for the bucket ops.
        rs_res = [r[0] for r in wire_state.get("rs", ())]
        ag_res = [r[0] for r in wire_state.get("ag", ())]
        # per-step AND per-shard dropout stream (reference semantics:
        # each rank draws independent masks); each microbatch folds its
        # index in on top
        base_rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(dropout_seed), state.step),
            collective.mesh_rank(data_axes))

        if inputs.shape[0] % accum_steps:
            raise ValueError(
                f"per-shard batch {inputs.shape[0]} does not divide into "
                f"accum_steps={accum_steps} microbatches")
        micro = inputs.shape[0] // accum_steps

        if sharded_tx:
            # the optimizer-state partition IS the bucket schedule
            schedule = state.opt_state.plan.schedule
        elif overlap_grads:
            schedule = _grad_schedule(state.params,
                                      collective.mesh_size(reduce_axes))
        else:
            schedule = None

        stats = state.batch_stats
        acc_shards, acc_grads, loss_sum = None, None, 0.0
        if pipelined:
            for k in range(accum_steps):
                xk = inputs[k * micro:(k + 1) * micro]
                yk = labels[k * micro:(k + 1) * micro]
                (loss_k, stats), grads_k = micro_grads(
                    state, stats, xk, yk, jax.random.fold_in(base_rng, k))
                loss_sum = loss_sum + loss_k
                if overlap_grads:
                    # reduce-scatter every bucket of THIS microbatch now:
                    # the next microbatch's backward has no data
                    # dependence on these collectives, so the latency-
                    # hiding scheduler overlaps them (reduce-scatter is
                    # linear — summing per-microbatch shards equals
                    # scattering the sum)
                    leaves_k = jax.tree_util.tree_leaves(grads_k)
                    rs_op = (state.opt_state.plan.op if sharded_tx
                             else tx.op)
                    shards_k = []
                    for i in range(len(schedule.buckets)):
                        if wire is None:
                            s = fusion.reduce_scatter_bucket(
                                schedule, i, leaves_k, op=rs_op)
                        else:
                            s, new_r = \
                                fusion.reduce_scatter_bucket_compressed(
                                    schedule, i, leaves_k, wire, op=rs_op,
                                    residual=(rs_res[i] if use_ef
                                              else None))
                            if use_ef:
                                rs_res[i] = new_r
                        shards_k.append(s)
                    acc_shards = (shards_k if acc_shards is None else
                                  [a + s for a, s in zip(acc_shards,
                                                         shards_k)])
                else:
                    acc_grads = (grads_k if acc_grads is None else
                                 jax.tree_util.tree_map(
                                     jnp.add, acc_grads, grads_k))
        else:
            (loss_sum, stats), grads = micro_grads(
                state, state.batch_stats, inputs, labels, base_rng)

        inv_k = 1.0 / accum_steps
        gnorm = None
        if overlap_grads:
            shards = [s * jnp.asarray(inv_k, s.dtype) for s in acc_shards]
            if tele_on:
                # shards partition the globally-averaged gradient: the
                # psum of shard sum-squares IS its exact norm² (the pad
                # zeros contribute nothing)
                local_sq = sum(jnp.sum(jnp.square(s.astype(jnp.float32)))
                               for s in shards)
                gnorm = jnp.sqrt(collective.allreduce(
                    local_sq, op=collective.Sum, axes=reduce_axes))
            if sharded_tx:
                grad_rows = {f"b{i}": s[None] for i, s in enumerate(shards)}
                if wire is None:
                    updates, opt_state = zero_lib.apply_shards(
                        tx.inner, grad_rows, state.opt_state, state.params)
                elif use_ef:
                    updates, opt_state, ag_res = zero_lib.apply_shards(
                        tx.inner, grad_rows, state.opt_state, state.params,
                        wire=wire, ag_residuals=ag_res)
                else:
                    updates, opt_state = zero_lib.apply_shards(
                        tx.inner, grad_rows, state.opt_state, state.params,
                        wire=wire)
            else:
                leaves, treedef = jax.tree_util.tree_flatten(state.params)
                new_leaves = [None] * len(leaves)
                for i, s in enumerate(shards):
                    if wire is None:
                        flat = fusion.all_gather_bucket(schedule, i, s)
                    else:
                        flat, new_r = fusion.all_gather_bucket_compressed(
                            schedule, i, s, wire,
                            residual=ag_res[i] if use_ef else None)
                        if use_ef:
                            ag_res[i] = new_r
                    for j, arr in fusion.unpack_bucket(
                            schedule, i, flat, leaves).items():
                        new_leaves[j] = arr
                grads = jax.tree_util.tree_unflatten(treedef, new_leaves)
                updates, opt_state = tx.update_preaveraged(
                    grads, state.opt_state, state.params)
        else:
            if pipelined:
                grads = jax.tree_util.tree_map(
                    lambda g: g * jnp.asarray(inv_k, g.dtype), acc_grads)
            if tele_on:
                # grads here are LOCAL (reduction happens inside tx):
                # the root-mean across ranks of local norm² — an upper
                # bound of the averaged-grad norm (Jensen), and the
                # divergence signal observability wants
                local_sq = sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads))
                gnorm = jnp.sqrt(collective.allreduce(
                    local_sq, op=collective.Average, axes=reduce_axes))
            updates, opt_state = tx.update(grads, state.opt_state,
                                           state.params)

        params = optax.apply_updates(state.params, updates)
        if stats:
            stats = jax.tree_util.tree_map(
                lambda x: collective.allreduce(x, op=collective.Average,
                                               axes=data_axes), stats)
        loss = collective.allreduce(loss_sum * inv_k,
                                    op=collective.Average, axes=data_axes)
        new_state = TrainState(params=params, opt_state=opt_state,
                               batch_stats=stats, step=state.step + 1)
        new_wire = {"rs": [r[None] for r in rs_res],
                    "ag": [r[None] for r in ag_res]}
        if tele_on:
            return new_state, new_wire, loss, gnorm
        return new_state, new_wire, loss

    wire_spec = P(tuple(reduce_axes))

    def outer(state, wire_state, inputs, labels):
        specs = state_specs(state)
        wspecs = jax.tree_util.tree_map(lambda _: wire_spec, wire_state)
        out_specs = ((specs, wspecs, P(), P()) if tele_on
                     else (specs, wspecs, P()))
        sharded = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(specs, wspecs, P(data_axes), P(data_axes)),
            out_specs=out_specs,
            check_vma=False)
        return sharded(state, wire_state, inputs, labels)

    # wire_state is an EMPTY pytree unless error feedback is on, so the
    # extra jit argument contributes zero buffers and the compiled
    # program stays byte-identical to the uncompressed build.
    jitted = jax.jit(outer, donate_argnums=(0, 1) if donate else ())
    place_data = _placer(mesh, P(data_axes))

    def place_state(state):
        return _placer(mesh, state_specs(state))(state)

    if loader is not None:
        # stage prefetched batches straight to this step's mesh placement
        # on the PRODUCER thread — by dispatch time place_data is a no-op
        loader.attach_placement(place_data, spec=P(data_axes))

    def _loader_batch():
        if loader is None:
            raise TypeError(
                "step(state) with no batch needs a loader — build the "
                "step with make_train_step(..., loader=...) or pass "
                "(inputs, labels) explicitly")
        batch = next(loader)
        if not (isinstance(batch, (tuple, list)) and len(batch) == 2):
            raise TypeError(
                "the loader's source must yield (inputs, labels) "
                f"batches for this step; got {type(batch).__name__} "
                f"of {len(batch) if hasattr(batch, '__len__') else '?'}")
        return batch[0], batch[1]

    _wire_holder = [None]

    def _wire_state_for(state):
        """Zero-initialized per-bucket residual buffers ([world, n] global,
        row r = rank r's carry), rebuilt lazily from the live state —
        rebuildable by construction, so never checkpointed."""
        if not use_ef:
            return {"rs": [], "ag": []}
        if sharded_tx:
            schedule = state.opt_state.plan.schedule
        else:
            # world from the mesh THIS step was built on (the global
            # mesh can be a different one — e.g. a sub-mesh step built
            # while a bigger mesh is set — and a mismatched world here
            # would shape the residual buffers against the wrong
            # schedule)
            mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
            schedule = _grad_schedule(
                state.params,
                int(np.prod([mesh_shape[a] for a in reduce_axes])))
        w = schedule.world

        def size_or_zero(i, n):
            # non-float buckets are never quantized (the bucket ops pass
            # their residual through untouched) — a zero-width buffer
            # keeps the per-bucket index alignment without the HBM or
            # donation traffic of a dead fp32 carry
            return n if jnp.issubdtype(schedule.buckets[i].dtype,
                                       jnp.floating) else 0

        ws = {"rs": [jnp.zeros((w, size_or_zero(i, p)), jnp.float32)
                     for i, p in enumerate(schedule.padded_sizes)],
              "ag": [jnp.zeros((w, size_or_zero(i, s)), jnp.float32)
                     for i, s in enumerate(schedule.shard_sizes)]}
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, jax.sharding.NamedSharding(mesh, wire_spec)), ws)

    def _wire_state(state):
        if _wire_holder[0] is None:
            _wire_holder[0] = _wire_state_for(state)
        return _wire_holder[0]

    def _reset_error_feedback():
        """Drop the carried residuals; the next step rebuilds zeros.
        Call after restoring ``state`` to an earlier commit (elastic
        rollback / checkpoint restore) so the compensation restarts
        clean instead of carrying a later step's error."""
        _wire_holder[0] = None

    from horovod_tpu.diag import recorder as _flightrec
    from horovod_tpu.telemetry import ledger as _ledger_lib
    # the goodput ledger settles at every step boundary: the interval
    # since the last settle, minus the stalls other subsystems charged
    # (data_wait, ckpt_stall, compile, ...), is booked as compute.
    # Resolved at CALL time (hvd.init opens a fresh run ledger); host-
    # side floats only — the compiled program is byte-identical with the
    # ledger on or off (tests/test_goodput.py).
    _goodput = _ledger_lib.get_ledger

    if not tele_on:
        _step_no = [0]

        def step(state, inputs=None, labels=None):
            # flight-recorder step boundaries (host-side only: with no
            # recorder installed these are a None check each, and they
            # never touch the traced computation — the compiled program
            # stays byte-identical either way, tests/test_diag.py)
            if inputs is None:
                inputs, labels = _loader_batch()
            n = _step_no[0]
            _step_no[0] = n + 1
            _check_wire_drift()
            _flightrec.step_begin(n)
            try:
                new_state, new_wire, loss = jitted(
                    place_state(state), _wire_state(state),
                    place_data(inputs), place_data(labels))
                _wire_holder[0] = new_wire
            except BaseException:
                # the residuals were donated into the failed dispatch and
                # may already be invalidated — drop them so the retry
                # path (elastic rollback) rebuilds zeros instead of
                # dying on deleted arrays forever
                _wire_holder[0] = None
                raise
            _flightrec.step_end(n)
            _goodput().settle_step()
            return new_state, loss
    else:
        from horovod_tpu import basics as _basics
        import time as _time

        instruments = telemetry_lib.StepInstruments(accum_steps=accum_steps)
        first_trace = [True]

        def step(state, inputs=None, labels=None):
            if inputs is None:
                inputs, labels = _loader_batch()
            step_no = int(instruments.steps.value)
            _check_wire_drift()
            _flightrec.step_begin(step_no)
            tl = _basics._state.timeline
            flow = None
            if tl is not None and first_trace[0]:
                # the first call traces: open an enclosing slice + flow
                # on the marker tid so the bucket markers emitted during
                # tracing link back to this dispatch (ops/fusion reads
                # _step_flow_id; flows need a B/E slice on their tid to
                # bind in Perfetto's legacy-JSON importer)
                tl.start_activity("marker", "step_trace_dispatch")
                flow = tl.flow_start("step_dispatch")
                tl._step_flow_id = flow
            t0 = _time.perf_counter()
            try:
                new_state, new_wire, loss, gnorm = jitted(
                    place_state(state), _wire_state(state),
                    place_data(inputs), place_data(labels))
                _wire_holder[0] = new_wire
            except BaseException:
                _wire_holder[0] = None  # donated into the failed dispatch
                raise
            finally:
                if flow is not None:
                    first_trace[0] = False
                    tl._step_flow_id = None
                    tl.flow_end("step_dispatch", flow)
                    tl.end_activity("marker")
            _flightrec.step_end(step_no)
            _goodput().settle_step()
            instruments.record_step(
                batch=int(inputs.shape[0]),
                dispatch_s=_time.perf_counter() - t0,
                loss=loss, grad_norm=gnorm, timeline=tl,
                step_no=instruments.steps.value)
            return new_state, loss

        step.instruments = instruments

    step.jitted = jitted  # AOT access (lower/compile/cost_analysis)
    step.reset_error_feedback = _reset_error_feedback
    step.loader = loader
    step.place_data = place_data
    step._settles_ledger = True  # elastic_train_loop must not re-settle

    def lower(state, inputs, labels):
        """AOT lower with the SAME placement the executed path uses, so
        the compile cache is shared and cost_analysis describes the
        module that actually runs."""
        return jitted.lower(place_state(state), _wire_state(state),
                            place_data(inputs), place_data(labels))

    step.lower = lower
    return step


def _spmd_gate(tx, what):
    """Shared validation for the GSPMD builders: version support and the
    optimizer contract. Returns the resolved wire format (``None`` or a
    compressor — the caller compiles it in-place: the shard_map island
    for chunked quantizers, dtype-narrowed constraints for casts)."""
    from horovod_tpu import compat, hvd_jax

    ok, reason = compat.gspmd_supported()
    if not ok:
        raise RuntimeError(
            f"{what}(spmd=True) needs the NamedSharding jit API: {reason}."
            " Use the explicit pipeline (spmd=False) on this jax — "
            "horovod_tpu/compat.py owns this gate.")
    if not isinstance(tx, hvd_jax.HorovodOptimizer):
        raise ValueError(
            f"{what}(spmd=True) needs the optimizer built by "
            "hvd.DistributedOptimizer(...) — the GSPMD step routes its "
            "gradient reduction through the plan")
    if tx.op != hvd_jax.Average:
        raise ValueError(
            f"the GSPMD step computes the global-batch mean loss — that "
            f"is op=Average semantics; got {tx.op!r}. Adasum/Min/Max "
            "reductions live on the explicit path (spmd=False)")
    if tx.backward_passes_per_step > 1:
        raise ValueError(
            "backward_passes_per_step>1 has no GSPMD path — its "
            "accumulator lives in the explicit pipeline")
    return tx.compression


class _SpmdProgram:
    """The shared machinery of both GSPMD step flavors (classification
    and LM): the lazily built jit wrapper — ``in_shardings``/
    ``out_shardings`` need the first state's tree structure, so the jit
    is constructed on first use and cached, one structure per step —
    plus the once-per-build compiled-collective accounting and the AOT
    lower. One copy, so a fix to either flavor cannot miss the other.

    ``arg_specs`` are the PartitionSpecs of the non-state args (batch
    leaves; each entry may be a pytree PREFIX for its argument — a
    single spec covers a whole subtree, which is how the wire-residual
    dict rides as one argument); ``n_scalar_outs`` counts the
    replicated scalar outputs after the state (loss, optional grad
    norm). ``aux_out_specs`` are specs for outputs BETWEEN the state
    and the scalars (the new wire-residual tree, sharded like its
    input); ``extra_donate`` names additional donated argnums (the
    residuals are dead after each step — donating them keeps the EF
    carry HBM-neutral, same as the explicit path's ``donate_argnums=
    (0, 1)``)."""

    def __init__(self, plan, global_step, arg_specs, n_scalar_outs,
                 donate, aux_out_specs=(), extra_donate=()):
        from horovod_tpu.parallel import gspmd as gspmd_lib

        self.plan = plan
        self._fn = global_step
        self._arg_specs = tuple(arg_specs)
        self._n_out = int(n_scalar_outs)
        self._aux_out_specs = tuple(aux_out_specs)
        self._extra_donate = tuple(extra_donate)
        self._donate = donate
        self.jitted = None
        self.state_shardings = None
        self._cache = gspmd_lib.CompiledProgramCache(mesh=plan.mesh)
        self.compiled_collectives = None
        self.compiled_axis_collectives = None

    def jitted_for(self, placed_state):
        from horovod_tpu.parallel import gspmd as gspmd_lib

        if self.jitted is None:
            self.state_shardings = gspmd_lib.state_shardings(
                self.plan, placed_state)
            rep = self.plan.sharding(P())
            self.jitted = jax.jit(
                self._fn,
                in_shardings=(self.state_shardings,) + tuple(
                    self.plan.sharding(s) for s in self._arg_specs),
                out_shardings=(self.state_shardings,) + tuple(
                    self.plan.sharding(s) for s in self._aux_out_specs)
                + (rep,) * self._n_out,
                donate_argnums=((0,) + self._extra_donate
                                if self._donate else ()))
        return self.jitted

    def executable(self, placed):
        """ONE compile per argument-shape signature: AOT lower+compile
        on first sight of a shape set (a shorter final batch from a
        ``drop_last=False`` loader, an eval batch), then the cached
        executable — the jit wrapper would retrace those transparently,
        and this cache keeps that behavior instead of crashing on a
        shape mismatch. The cache/accounting machinery is the shared
        ``gspmd.CompiledProgramCache`` (the serving engine wraps the
        same one): executables are called directly, and each new
        program's collectives are accounted as it compiles — the same
        once-per-compile semantics as the trace-time counters. Donation
        and in/out shardings were fixed at jit construction and carry
        into every executable."""
        ex = self._cache.executable(self.jitted_for(placed[0]), placed)
        self.compiled_collectives = self._cache.last_collectives
        self.compiled_axis_collectives = \
            self._cache.last_axis_collectives
        return ex

    def lower(self, placed):
        """AOT lower with the executed path's placement — for
        ``cost_analysis``-style callers; ``.compile()`` on the result
        is a fresh compile (the executing path's artifact is
        :meth:`executable`)."""
        return self.jitted_for(placed[0]).lower(*placed)


def _spmd_wire_drift_checker(tx, wire):
    """Per-step guard mirroring the explicit path's _check_wire_drift:
    the GSPMD builders resolve the wire format ONCE at build and
    compile it into the program (the chunked shard_map island, the
    cast-narrowed constraints, or neither), but config.wire_dtype binds
    late — an autotuner that installs its winner AFTER the step was
    built would otherwise leave tx.compression claiming a format the
    running program never applies (or vice versa). Warn once, in either
    drift direction, instead of silently diverging."""
    warned = [False]

    def check():
        if warned[0]:
            return
        now = tx.compression
        if now is not wire:
            warned[0] = True
            import warnings
            built = (f"built with {wire.name!r}" if wire is not None
                     else "built uncompressed")
            warnings.warn(
                f"tx.compression now resolves to "
                f"{getattr(now, 'name', None)!r} but this GSPMD step was "
                f"{built} — the wire format is compiled into the program "
                "at make_train_step time. Rebuild the step after "
                "installing config.wire_dtype for the new format to "
                "take effect.", stacklevel=3)

    return check


def _make_spmd_train_step(model, tx, mesh=None,
                          loss_fn=softmax_cross_entropy, batch_axes=None,
                          donate=True, dropout_seed=0, accum_steps=1,
                          overlap_grads=False, telemetry=None,
                          error_feedback=True, loader=None):
    """The GSPMD hot path behind ``make_train_step(spmd=True)`` — see
    that docstring and ``parallel/gspmd.py`` for the contract.

    Wire compression compiles IN-PLACE (no fallback):

    * **Chunked quantizers** (fp8/int8) need per-device partial
      gradients and per-chunk scales, which no annotation can express —
      so the per-shard forward/backward + quantized bucket exchange +
      optimizer tail run as ONE ``shard_map`` island
      (``gspmd.shard_map_island``) inside the jitted program. XLA's
      latency-hiding scheduler still owns the schedule; the wire moves
      narrow bytes (all-to-all of int8/fp8 rows + fp32 scales).
      Semantics inside the island are the EXPLICIT path's: per-shard
      BatchNorm statistics (averaged after) and per-shard dropout
      streams — not the annotation path's sync-BN/global stream.
    * **Cast wires** (bf16/float16) keep the annotation-only global
      program (sync-BN, one dropout stream): ZeRO-1's constraint
      exchange narrows both halves by dtype-narrowed constraints
      (``gspmd.apply_shards_spmd(wire=...)``, with delta-EF on the
      all-gather half); the plain-DP path round-trips the logical
      gradient through the wire dtype as a convert-sinking hint.
    * ``wire is None`` compiles the byte-identical uncompressed program
      (the wire-residual argument is an empty pytree — zero buffers).
    """
    import time as _time

    from horovod_tpu import telemetry as telemetry_lib
    from horovod_tpu.ops import fusion
    from horovod_tpu.parallel import gspmd as gspmd_lib
    from horovod_tpu.parallel import zero as zero_lib

    wire = _spmd_gate(tx, "make_train_step")
    if accum_steps != 1 or overlap_grads:
        raise ValueError(
            "accum_steps/overlap_grads are the explicit pipeline's "
            "microbatch knobs; the GSPMD step compiles the whole batch "
            "and XLA's latency-hiding scheduler owns the compute/comms "
            "overlap")

    tele_on = (telemetry_lib.enabled() if telemetry is None
               else bool(telemetry))
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    plan = gspmd_lib.derive_plan(mesh)
    data_axes = tuple(batch_axes) if batch_axes else plan.data_axes
    batch_spec = P(data_axes)

    sharded_tx = tx.sharded_update
    reduce_axes = (tuple(tx.axes) if tx.axes is not None else data_axes)
    chunked = wire is not None and getattr(wire, "chunked", False)
    # EF carries exist where a step-to-step residual is well-defined:
    # both halves of the chunked island exchange, and the delta
    # all-gather of the cast+ZeRO-1 annotation path. The cast plain-DP
    # hint is stateless (a residual would have to be added to the
    # still-unreduced logical gradient — see apply_shards_spmd).
    use_ef = (wire is not None and error_feedback
              and (chunked or sharded_tx))
    wire_spec = P(tuple(reduce_axes))

    def _grad_schedule(params, world):
        return fusion.bucket_schedule(
            jax.tree_util.tree_leaves(params), world=world,
            threshold_bytes=tx.threshold_bytes, axes=reduce_axes,
            hierarchical=tx._hierarchical_resolved())

    if chunked:
        def local_step(state, wire_state, inputs, labels):
            # the shard_map island: per-shard forward/backward feeding
            # the chunked quantize->alltoall->dequantize bucket exchange
            # — the same data plane as the explicit overlap pipeline,
            # but compiled INSIDE the GSPMD jit step so the surrounding
            # program (and its scheduler) stays XLA's. Residual rows
            # arrive as this shard's [1, n] slice of the [world, n]
            # global carry; squeeze for the bucket ops.
            rs_res = [r[0] for r in wire_state.get("rs", ())]
            ag_res = [r[0] for r in wire_state.get("ag", ())]
            # per-step AND per-shard dropout stream — explicit-path
            # semantics (each rank draws independent masks)
            rng = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(dropout_seed),
                                   state.step),
                collective.mesh_rank(data_axes))

            def compute_loss(params):
                variables = {"params": params}
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                    logits, mutated = model.apply(
                        variables, inputs, train=True,
                        mutable=["batch_stats"], rngs={"dropout": rng})
                    return loss_fn(logits, labels), mutated["batch_stats"]
                logits = model.apply(variables, inputs, train=True,
                                     rngs={"dropout": rng})
                return loss_fn(logits, labels), {}

            (loss, stats), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(state.params)

            if sharded_tx:
                # the optimizer-state partition IS the bucket schedule
                schedule = state.opt_state.plan.schedule
                rs_op = state.opt_state.plan.op
            else:
                schedule = _grad_schedule(
                    state.params, collective.mesh_size(reduce_axes))
                rs_op = tx.op
            leaves_g = jax.tree_util.tree_leaves(grads)
            shards = []
            for i in range(len(schedule.buckets)):
                s, new_r = fusion.reduce_scatter_bucket_compressed(
                    schedule, i, leaves_g, wire, op=rs_op,
                    residual=(rs_res[i] if use_ef else None))
                if use_ef:
                    rs_res[i] = new_r
                shards.append(s)
            gnorm = None
            if tele_on:
                # shards partition the globally-averaged gradient: the
                # psum of shard sum-squares IS its exact norm²
                local_sq = sum(jnp.sum(jnp.square(s.astype(jnp.float32)))
                               for s in shards)
                gnorm = jnp.sqrt(collective.allreduce(
                    local_sq, op=collective.Sum, axes=reduce_axes))
            if sharded_tx:
                grad_rows = {f"b{i}": s[None]
                             for i, s in enumerate(shards)}
                if use_ef:
                    updates, opt_state, ag_res = zero_lib.apply_shards(
                        tx.inner, grad_rows, state.opt_state,
                        state.params, wire=wire, ag_residuals=ag_res)
                else:
                    updates, opt_state = zero_lib.apply_shards(
                        tx.inner, grad_rows, state.opt_state,
                        state.params, wire=wire)
            else:
                leaves_p, treedef = jax.tree_util.tree_flatten(
                    state.params)
                new_leaves = [None] * len(leaves_p)
                for i, s in enumerate(shards):
                    flat, new_r = fusion.all_gather_bucket_compressed(
                        schedule, i, s, wire,
                        residual=ag_res[i] if use_ef else None)
                    if use_ef:
                        ag_res[i] = new_r
                    for j, arr in fusion.unpack_bucket(
                            schedule, i, flat, leaves_p).items():
                        new_leaves[j] = arr
                grads_full = jax.tree_util.tree_unflatten(treedef,
                                                          new_leaves)
                updates, opt_state = tx.update_preaveraged(
                    grads_full, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            if stats:
                stats = jax.tree_util.tree_map(
                    lambda x: collective.allreduce(
                        x, op=collective.Average, axes=data_axes), stats)
            loss = collective.allreduce(loss, op=collective.Average,
                                        axes=data_axes)
            new_state = TrainState(params=params, opt_state=opt_state,
                                   batch_stats=stats,
                                   step=state.step + 1)
            new_wire = {"rs": [r[None] for r in rs_res],
                        "ag": [r[None] for r in ag_res]}
            if tele_on:
                return new_state, new_wire, loss, gnorm
            return new_state, new_wire, loss

        def global_step(state, wire_state, inputs, labels):
            specs = state_specs(state)
            wspecs = jax.tree_util.tree_map(lambda _: wire_spec,
                                            wire_state)
            out_specs = ((specs, wspecs, P(), P()) if tele_on
                         else (specs, wspecs, P()))
            island = gspmd_lib.shard_map_island(
                local_step, plan,
                in_specs=(specs, wspecs, batch_spec, batch_spec),
                out_specs=out_specs)
            return island(state, wire_state, inputs, labels)
    else:
        def global_step(state, wire_state, inputs, labels):
            # ONE global dropout stream per step: there is no per-shard
            # rank to fold in — masks are drawn over the global batch
            # (the explicit path draws per-shard streams;
            # docs/PERFORMANCE.md)
            rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed),
                                     state.step)

            def compute_loss(params):
                variables = {"params": params}
                if state.batch_stats:
                    variables["batch_stats"] = state.batch_stats
                    logits, mutated = model.apply(
                        variables, inputs, train=True,
                        mutable=["batch_stats"], rngs={"dropout": rng})
                    return loss_fn(logits, labels), mutated["batch_stats"]
                logits = model.apply(variables, inputs, train=True,
                                     rngs={"dropout": rng})
                return loss_fn(logits, labels), {}

            (loss, stats), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(state.params)
            if wire is not None and not sharded_tx:
                # plain DP has no sharded consumer to hang a narrow
                # constraint on: round-trip the logical gradient through
                # the wire dtype — the applied update carries the wire
                # precision, and the convert adjacent to XLA's inserted
                # all-reduce is the cue for sinking the reduction to the
                # narrow width where the backend can
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(wire.wire_dtype).astype(g.dtype)
                               if jnp.issubdtype(g.dtype, jnp.floating)
                               else g), grads)
            gnorm = None
            if tele_on:
                # grads are the logical global-mean gradient — this is
                # its exact L2 norm (same definition as the overlapped
                # path)
                gnorm = jnp.sqrt(sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree_util.tree_leaves(grads)))
            if wire is not None and sharded_tx:
                ag_res = list(wire_state.get("ag", ()))
                if use_ef:
                    updates, opt_state, ag_res = tx.update_spmd(
                        grads, state.opt_state, state.params, plan,
                        wire=wire, ag_residuals=ag_res)
                else:
                    updates, opt_state = tx.update_spmd(
                        grads, state.opt_state, state.params, plan,
                        wire=wire)
                new_wire = {"rs": [], "ag": ag_res if use_ef else []}
            else:
                updates, opt_state = tx.update_spmd(
                    grads, state.opt_state, state.params, plan)
                new_wire = {"rs": [], "ag": []}
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(params=params, opt_state=opt_state,
                                   batch_stats=stats,
                                   step=state.step + 1)
            if tele_on:
                return new_state, new_wire, loss, gnorm
            return new_state, new_wire, loss

    place_data = _placer(mesh, batch_spec)

    def place_state(state):
        # ONE placement implementation (parallel/gspmd.place_state);
        # once the program is built, its cached shardings tree is
        # reused instead of re-deriving specs on every step
        if prog.state_shardings is not None:
            return jax.tree_util.tree_map(_put, state,
                                          prog.state_shardings)
        return gspmd_lib.place_state(plan, state)

    if loader is not None:
        # prefetched batches are staged by the PRODUCER thread directly
        # onto the plan's batch NamedSharding — they arrive matching the
        # compiled step's in_shardings, so dispatch-time placement is a
        # no-op
        loader.attach_placement(place_data,
                                spec=plan.sharding(batch_spec))

    def _loader_batch():
        if loader is None:
            raise TypeError(
                "step(state) with no batch needs a loader — build the "
                "step with make_train_step(..., loader=...) or pass "
                "(inputs, labels) explicitly")
        batch = next(loader)
        if not (isinstance(batch, (tuple, list)) and len(batch) == 2):
            raise TypeError(
                "the loader's source must yield (inputs, labels) "
                f"batches for this step; got {type(batch).__name__}")
        return batch[0], batch[1]

    # the wire-residual carry (error feedback on) rides as ONE extra
    # jit argument — a dict of per-bucket [world, n] fp32 arrays,
    # sharded over the scatter axes — and comes back as the matching
    # extra output. With EF off (including compression off) the
    # argument is OMITTED entirely, keeping the program — down to its
    # result metadata — byte-identical to a build with no wire
    # plumbing at all.
    if use_ef:
        prog = _SpmdProgram(plan, global_step,
                            arg_specs=(wire_spec, batch_spec, batch_spec),
                            n_scalar_outs=2 if tele_on else 1,
                            donate=donate,
                            aux_out_specs=(wire_spec,),
                            extra_donate=(1,))
    else:
        def _global_step_stateless(state, inputs, labels):
            out = global_step(state, {"rs": [], "ag": []}, inputs,
                              labels)
            return (out[0],) + out[2:]  # drop the empty wire slot

        # keep the jitted module's name (jit_global_step) — the
        # compression-off program must be byte-identical, debug
        # metadata included
        _global_step_stateless.__name__ = "global_step"
        _global_step_stateless.__qualname__ = global_step.__qualname__
        prog = _SpmdProgram(plan, _global_step_stateless,
                            arg_specs=(batch_spec, batch_spec),
                            n_scalar_outs=2 if tele_on else 1,
                            donate=donate)
    _check_wire_drift = _spmd_wire_drift_checker(tx, wire)

    _wire_holder = [None]

    def _wire_state_for(state):
        """Zero-initialized residual buffers ([world, n] global, row r =
        rank r's carry), rebuilt lazily from the live state —
        rebuildable by construction, so never checkpointed. The chunked
        island carries both exchange halves; the cast+ZeRO-1 annotation
        path carries the delta all-gather half only."""
        if sharded_tx:
            schedule = state.opt_state.plan.schedule
        else:
            mesh_shape = dict(zip(mesh.axis_names, mesh.devices.shape))
            schedule = _grad_schedule(
                state.params,
                int(np.prod([mesh_shape[a] for a in reduce_axes])))
        w = schedule.world

        def size_or_zero(i, n):
            # non-float buckets are never quantized — zero-width buffer
            # keeps per-bucket index alignment without dead HBM traffic
            return n if jnp.issubdtype(schedule.buckets[i].dtype,
                                       jnp.floating) else 0

        rs = ([jnp.zeros((w, size_or_zero(i, p)), jnp.float32)
               for i, p in enumerate(schedule.padded_sizes)]
              if chunked else [])
        ag = [jnp.zeros((w, size_or_zero(i, s)), jnp.float32)
              for i, s in enumerate(schedule.shard_sizes)]
        return jax.tree_util.tree_map(
            lambda x: _put(x, plan.sharding(wire_spec)),
            {"rs": rs, "ag": ag})

    def _wire_state(state):
        if _wire_holder[0] is None:
            _wire_holder[0] = _wire_state_for(state)
        return _wire_holder[0]

    def _reset_error_feedback():
        """Drop the carried residuals; the next step rebuilds zeros
        (call after rolling ``state`` back to an earlier commit)."""
        _wire_holder[0] = None

    from horovod_tpu.diag import recorder as _flightrec
    from horovod_tpu.telemetry import ledger as _ledger_lib

    instruments = (telemetry_lib.StepInstruments() if tele_on else None)
    _step_no = [0]

    def step(state, inputs=None, labels=None):
        if inputs is None:
            inputs, labels = _loader_batch()
        n = _step_no[0]
        _step_no[0] = n + 1
        _flightrec.step_begin(n)
        if use_ef:
            placed = (place_state(state), _wire_state(state),
                      place_data(inputs), place_data(labels))
        else:
            placed = (place_state(state), place_data(inputs),
                      place_data(labels))
        _check_wire_drift()
        ex = prog.executable(placed)  # one compile per shape signature
        step.jitted = prog.jitted
        step.compiled_collectives = prog.compiled_collectives
        step.compiled_axis_collectives = prog.compiled_axis_collectives
        t0 = _time.perf_counter()
        try:
            outs = ex(*placed)
        except BaseException:
            # the residuals were donated into the failed dispatch —
            # drop them so a retry rebuilds zeros instead of dying on
            # deleted arrays
            _wire_holder[0] = None
            raise
        if use_ef:
            new_state, rest = outs[0], outs[2:]
            _wire_holder[0] = outs[1]
        else:
            new_state, rest = outs[0], outs[1:]
        loss = rest[0]
        gnorm = rest[1] if tele_on else None
        _flightrec.step_end(n)
        ledger = _ledger_lib.get_ledger()
        ledger.note_compiled_path()
        ledger.settle_step()
        if instruments is not None:
            instruments.record_step(
                batch=int(inputs.shape[0]),
                dispatch_s=_time.perf_counter() - t0,
                loss=loss, grad_norm=gnorm,
                step_no=instruments.steps.value)
        return new_state, loss

    def xray(state, inputs=None, labels=None, k=3, profile_dir=None):
        """Opt-in compiled-step X-ray: run K steps of the ALREADY
        compiled executable under a device trace and attribute where
        the device time went (telemetry/xprof.py). Capture wraps
        around the dispatch — the compiled program is byte-identical
        with X-ray off. State threads through the captured steps
        (donation as usual): returns ``(new_state, summary)``."""
        from horovod_tpu.telemetry import xprof as _xprof
        if inputs is None:
            inputs, labels = _loader_batch()
        return _xprof.xray_run(
            step, state, (inputs, labels), k=k, profile_dir=profile_dir,
            compiled_collectives=lambda: step.compiled_collectives)

    def lower(state, inputs, labels):
        if use_ef:
            placed = (place_state(state), _wire_state(state),
                      place_data(inputs), place_data(labels))
        else:
            placed = (place_state(state), place_data(inputs),
                      place_data(labels))
        lowered = prog.lower(placed)
        step.jitted = prog.jitted
        return lowered

    if instruments is not None:
        step.instruments = instruments
    step.jitted = None  # set at first build
    step.lower = lower
    step.reset_error_feedback = _reset_error_feedback
    step.loader = loader
    step.place_data = place_data
    step.plan = plan
    step.spmd = True
    step.compiled_collectives = None  # set at first call
    step.compiled_axis_collectives = None
    step._settles_ledger = True
    step.xray = xray
    return step


def _make_spmd_lm_train_step(model, tx, mesh=None, batch_axis="data",
                             donate=True):
    """The GSPMD LM step behind ``make_lm_train_step(spmd=True)``:
    next-token mean loss over the batch-sharded tokens.

    Wire compression compiles IN-PLACE, mirroring
    ``_make_spmd_train_step``: chunked quantizers (fp8/int8) run the
    per-shard forward/backward + quantized bucket exchange as a
    ``shard_map`` island inside the jitted program; cast wires keep the
    annotation-only global program (dtype-narrowed constraints under
    ZeRO-1, a round-trip convert hint under plain DP). LM compression
    is STATELESS — no error-feedback carry — matching the explicit LM
    step's ``fused_allreduce`` route, so ``step(state, tokens)`` keeps
    its two-argument signature and the two builds stay head-to-head
    comparable in ``bench.py``."""
    from horovod_tpu.ops import fusion
    from horovod_tpu.parallel import gspmd as gspmd_lib
    from horovod_tpu.parallel import zero as zero_lib

    wire = _spmd_gate(tx, "make_lm_train_step")
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    plan = gspmd_lib.derive_plan(mesh)
    token_spec = P(batch_axis)
    sharded_tx = tx.sharded_update
    reduce_axes = (tuple(tx.axes) if tx.axes is not None
                   else (batch_axis,))
    chunked = wire is not None and getattr(wire, "chunked", False)

    def _local_loss(params, tokens):
        logits = model.apply({"params": params}, tokens)
        targets = tokens[:, 1:]
        logits_t = (logits[:, :-1]
                    if targets.shape[1] == logits.shape[1] - 1
                    else logits)
        logp = jax.nn.log_softmax(logits_t.astype(jnp.float32),
                                  axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None],
                                 axis=-1)[..., 0]
        return -jnp.mean(ll)

    if chunked:
        def local_step(state, tokens):
            # the shard_map island (see _make_spmd_train_step): the
            # per-shard mean over an equal token shard, averaged across
            # shards, IS the exact global mean
            loss, grads = jax.value_and_grad(_local_loss)(state.params,
                                                          tokens)
            if sharded_tx:
                schedule = state.opt_state.plan.schedule
                rs_op = state.opt_state.plan.op
            else:
                schedule = fusion.bucket_schedule(
                    jax.tree_util.tree_leaves(state.params),
                    world=collective.mesh_size(reduce_axes),
                    threshold_bytes=tx.threshold_bytes,
                    axes=reduce_axes,
                    hierarchical=tx._hierarchical_resolved())
                rs_op = tx.op
            leaves_g = jax.tree_util.tree_leaves(grads)
            shards = []
            for i in range(len(schedule.buckets)):
                s, _ = fusion.reduce_scatter_bucket_compressed(
                    schedule, i, leaves_g, wire, op=rs_op)
                shards.append(s)
            if sharded_tx:
                grad_rows = {f"b{i}": s[None]
                             for i, s in enumerate(shards)}
                updates, opt_state = zero_lib.apply_shards(
                    tx.inner, grad_rows, state.opt_state, state.params,
                    wire=wire)
            else:
                leaves_p, treedef = jax.tree_util.tree_flatten(
                    state.params)
                new_leaves = [None] * len(leaves_p)
                for i, s in enumerate(shards):
                    flat, _ = fusion.all_gather_bucket_compressed(
                        schedule, i, s, wire)
                    for j, arr in fusion.unpack_bucket(
                            schedule, i, flat, leaves_p).items():
                        new_leaves[j] = arr
                grads_full = jax.tree_util.tree_unflatten(treedef,
                                                          new_leaves)
                updates, opt_state = tx.update_preaveraged(
                    grads_full, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            loss = collective.allreduce(loss, op=collective.Average,
                                        axes=(batch_axis,))
            new_state = TrainState(params=params, opt_state=opt_state,
                                   batch_stats=state.batch_stats,
                                   step=state.step + 1)
            return new_state, loss

        def global_step(state, tokens):
            specs = state_specs(state)
            island = gspmd_lib.shard_map_island(
                local_step, plan,
                in_specs=(specs, token_spec),
                out_specs=(specs, P()))
            return island(state, tokens)
    else:
        def global_step(state, tokens):
            # the global mean IS the exact loss — no allreduce of
            # per-shard partial means to get right
            loss, grads = jax.value_and_grad(_local_loss)(state.params,
                                                          tokens)
            if wire is not None and not sharded_tx:
                # plain DP: round-trip through the wire dtype as the
                # convert-sinking hint (see _make_spmd_train_step)
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(wire.wire_dtype).astype(g.dtype)
                               if jnp.issubdtype(g.dtype, jnp.floating)
                               else g), grads)
            if wire is not None and sharded_tx:
                updates, opt_state = tx.update_spmd(
                    grads, state.opt_state, state.params, plan,
                    wire=wire)
            else:
                updates, opt_state = tx.update_spmd(
                    grads, state.opt_state, state.params, plan)
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(params=params, opt_state=opt_state,
                                   batch_stats=state.batch_stats,
                                   step=state.step + 1)
            return new_state, loss

    place_tokens = _placer(mesh, token_spec)

    def place_state(state):
        if prog.state_shardings is not None:
            return jax.tree_util.tree_map(_put, state,
                                          prog.state_shardings)
        return gspmd_lib.place_state(plan, state)

    prog = _SpmdProgram(plan, global_step, arg_specs=(token_spec,),
                        n_scalar_outs=1, donate=donate)
    _check_wire_drift = _spmd_wire_drift_checker(tx, wire)

    from horovod_tpu.diag import recorder as _flightrec
    from horovod_tpu.telemetry import ledger as _ledger_lib
    _step_no = [0]

    def step(state, tokens):
        n = _step_no[0]
        _step_no[0] = n + 1
        _flightrec.step_begin(n)
        placed = (place_state(state), place_tokens(tokens))
        _check_wire_drift()
        ex = prog.executable(placed)  # one compile per shape signature
        step.jitted = prog.jitted
        step.compiled_collectives = prog.compiled_collectives
        step.compiled_axis_collectives = prog.compiled_axis_collectives
        out = ex(*placed)
        _flightrec.step_end(n)
        ledger = _ledger_lib.get_ledger()
        ledger.note_compiled_path()
        ledger.settle_step()
        return out

    def lower(state, tokens):
        placed = (place_state(state), place_tokens(tokens))
        lowered = prog.lower(placed)
        step.jitted = prog.jitted
        return lowered

    def xray(state, tokens, k=3, profile_dir=None):
        """Compiled-step X-ray for the LM step — see the ResNet twin:
        K traced executions of the already-compiled program, device
        time attributed by telemetry/xprof.py. Returns
        ``(new_state, summary)``."""
        from horovod_tpu.telemetry import xprof as _xprof
        return _xprof.xray_run(
            step, state, (tokens,), k=k, profile_dir=profile_dir,
            compiled_collectives=lambda: step.compiled_collectives)

    step.jitted = None
    step.lower = lower
    step.plan = plan
    step.spmd = True
    step.compiled_collectives = None
    step.compiled_axis_collectives = None
    step._settles_ledger = True
    step.xray = xray
    return step


def elastic_train_loop(elastic_state, train_step, batch_fn, num_steps,
                       commit_every=1, checkpoint_every=None,
                       on_step=None):
    """Drive ``train_step`` under the elastic retry loop
    (``hvd.elastic.run``): commit/restore/sync semantics come from
    ``elastic_state`` (a ``hvd.elastic.JaxState`` whose ``train_state``
    attribute holds the :class:`TrainState`), membership interrupts are
    honored at commit boundaries, and a worker failure rolls back to the
    last commit before retrying.

    ``batch_fn`` supplies data two ways: a callable ``batch_fn(step) ->
    (inputs, labels)`` (step-indexed so a restored worker re-reads the
    right batch), or a ``horovod_tpu.data.PrefetchLoader`` — then the
    loop pulls prefetched batches, attaches the loader to
    ``elastic_state`` (when it is a ``JaxState``) so the loader's
    cursor commits, restores and re-syncs WITH the model state, and a
    rollback after a worker failure replays the exact batches of the
    rolled-back steps. ``on_step(step, loss)`` is an optional observer.
    Returns the final ``TrainState``.

    ``checkpoint_every=K`` sets the DISK cadence independently of the
    in-memory ``commit_every``: every K-th commit is persisted through
    the async sharded checkpoint subsystem (``horovod_tpu/ckpt``,
    docs/CHECKPOINT.md), where the training stall is only the
    device→host snapshot — the serialize/fsync/manifest commit overlaps
    the following steps (``hvd_ckpt_blocking_seconds`` vs
    ``hvd_ckpt_save_seconds``). Requires a ``JaxState`` built with a
    ``directory``; the final commit always flushes to disk.

    When telemetry is enabled and ``train_step`` is not already an
    instrumented ``make_train_step`` build, the loop records step
    latency / examples-per-sec itself, so a hand-written step function
    still shows up on the metrics plane.
    """
    import time as _time

    from horovod_tpu import elastic as _elastic
    from horovod_tpu import telemetry as telemetry_lib

    if checkpoint_every is not None:
        if not getattr(elastic_state, "_directory", None):
            raise ValueError(
                "checkpoint_every needs an elastic state with a "
                "checkpoint directory (JaxState(directory=...))")
        elastic_state.checkpoint_every = max(1, int(checkpoint_every))

    loader = (batch_fn if hasattr(batch_fn, "cursor")
              and hasattr(batch_fn, "__next__") else None)
    if loader is not None and hasattr(elastic_state, "attach_loader"):
        # cursor rides the commit/restore/sync/manifest machinery
        elastic_state.attach_loader(loader)

    own_instruments = None
    if telemetry_lib.enabled() and not hasattr(train_step, "instruments"):
        own_instruments = telemetry_lib.StepInstruments()

    from horovod_tpu.telemetry import ledger as _ledger_lib
    # a hand-written train_step doesn't settle the goodput ledger itself
    # — the loop does it, so its steps still get time attribution
    _goodput = (None if getattr(train_step, "_settles_ledger", False)
                else _ledger_lib.get_ledger)

    def _batch_of(inputs):
        # hand-written steps may take pytree batches; any leaf's leading
        # dim is the per-call example count (0 when unknowable)
        leaves = jax.tree_util.tree_leaves(inputs)
        try:
            return int(leaves[0].shape[0])
        except (IndexError, AttributeError, TypeError):
            return 0

    def _step_of(ts):
        return int(jax.device_get(ts.step))

    @_elastic.run
    def _loop(state):
        while _step_of(state.train_state) < num_steps:
            if loader is not None:
                inputs, labels = next(loader)
            else:
                inputs, labels = batch_fn(_step_of(state.train_state))
            t0 = _time.perf_counter()
            new_ts, loss = train_step(state.train_state, inputs, labels)
            if _goodput is not None:
                _goodput().settle_step()
            if own_instruments is not None:
                from horovod_tpu import basics as _basics
                own_instruments.record_step(
                    batch=_batch_of(inputs),
                    dispatch_s=_time.perf_counter() - t0, loss=loss,
                    timeline=_basics._state.timeline)
            state.train_state = new_ts
            done = _step_of(new_ts)
            if on_step is not None:
                on_step(done, float(jax.device_get(loss)))
            if done % commit_every == 0 or done >= num_steps:
                if done >= num_steps and hasattr(state, "checkpoint_every"):
                    # the final commit must reach disk regardless of the
                    # thinned cadence — but the cadence itself must
                    # survive (an elastic retry re-enters this loop with
                    # the same state object)
                    cadence = state.checkpoint_every
                    state.checkpoint_every = 1
                    try:
                        state.commit()
                    finally:
                        state.checkpoint_every = cadence
                else:
                    state.commit()
        state.flush()  # drain any async save before leaving the loop
        return state.train_state

    return _loop(elastic_state)


def make_lm_train_step(model, tx, mesh=None, batch_axis="data",
                       seq_axis=None, donate=True, spmd=False):
    """Build a jitted SPMD language-model train step (next-token loss).

    ``spmd=True`` selects the GSPMD hot path (no explicit collectives;
    see ``make_train_step``). It shards the batch axis only — ring
    attention over ``seq_axis`` is an explicit shard_map schedule and
    stays on the default path.

    ``tokens`` is ``[B, S]``; B is sharded over ``batch_axis`` and, when
    ``seq_axis`` is set, S over ``seq_axis`` with ring attention inside the
    model (``cfg.sequence_axis`` must name the same axis). The next-token
    loss is **exact** under sequence sharding: each shard's final position
    is scored against the first token of the next shard (fetched with one
    ``ppermute`` over ``seq_axis``), only the global final position is
    masked, and the mean is normalized by the global target count — so the
    seq-parallel loss and gradient match the single-device full-sequence
    computation.
    """
    if spmd:
        if seq_axis is not None:
            raise ValueError(
                "make_lm_train_step(spmd=True) shards the batch axis "
                "only; ring attention (seq_axis) is the explicit path's "
                "shard_map schedule — drop seq_axis or spmd")
        return _make_spmd_lm_train_step(model, tx, mesh=mesh,
                                        batch_axis=batch_axis,
                                        donate=donate)
    mesh = mesh if mesh is not None else mesh_lib.get_mesh()
    grad_axes = (batch_axis,) if seq_axis is None else (batch_axis, seq_axis)
    n_shards = int(np.prod([mesh.shape[a] for a in grad_axes]))
    n_seq = mesh.shape[seq_axis] if seq_axis else 1

    def local_step(state, tokens):
        if seq_axis is not None and n_seq > 1:
            # shard i's final target is shard i+1's first token; the wrap
            # pair (0 -> n-1) is masked below as the global final position
            nxt = jax.lax.ppermute(
                tokens[:, :1], seq_axis,
                perm=[((i + 1) % n_seq, i) for i in range(n_seq)])
            targets = jnp.concatenate([tokens[:, 1:], nxt], axis=1)
            is_last = jax.lax.axis_index(seq_axis) == n_seq - 1
            mask = jnp.ones(targets.shape, jnp.float32)
            mask = mask.at[:, -1].set(jnp.where(is_last, 0.0, 1.0))
        else:
            targets = tokens[:, 1:]
            mask = jnp.ones(targets.shape, jnp.float32)

        def compute_loss(params):
            logits = model.apply({"params": params}, tokens)
            if targets.shape[1] == logits.shape[1] - 1:
                logits_t = logits[:, :-1]
            else:
                logits_t = logits
            logp = jax.nn.log_softmax(logits_t.astype(jnp.float32), axis=-1)
            ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
            local_sum = -jnp.sum(ll * mask)
            global_count = collective.allreduce(
                jnp.asarray(jnp.sum(mask), jnp.float32), op=collective.Sum,
                axes=grad_axes)
            # scaled so that the Average-allreduce of per-shard losses (and
            # of per-shard gradients, inside ``tx``) equals the exact
            # global-mean loss/gradient
            return local_sum * n_shards / global_count

        loss, grads = jax.value_and_grad(compute_loss)(state.params)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        loss = collective.allreduce(loss, op=collective.Average,
                                    axes=grad_axes)
        new_state = TrainState(params=params, opt_state=opt_state,
                               batch_stats=state.batch_stats,
                               step=state.step + 1)
        return new_state, loss

    token_spec = P(batch_axis, seq_axis) if seq_axis else P(batch_axis)

    def outer(state, tokens):
        specs = state_specs(state)
        sharded = jax.shard_map(
            local_step, mesh=mesh,
            in_specs=(specs, token_spec),
            out_specs=(specs, P()),
            check_vma=False)
        return sharded(state, tokens)

    jitted = jax.jit(outer, donate_argnums=(0,) if donate else ())
    place_tokens = _placer(mesh, token_spec)

    def place_state(state):
        return _placer(mesh, state_specs(state))(state)

    from horovod_tpu.diag import recorder as _flightrec
    from horovod_tpu.telemetry import ledger as _ledger_lib
    _step_no = [0]

    def step(state, tokens):
        n = _step_no[0]
        _step_no[0] = n + 1
        _flightrec.step_begin(n)
        out = jitted(place_state(state), place_tokens(tokens))
        _flightrec.step_end(n)
        _ledger_lib.get_ledger().settle_step()
        return out

    step.jitted = jitted  # AOT access (lower/compile/cost_analysis)
    step._settles_ledger = True

    def lower(state, tokens):
        """AOT lower with the SAME placement the executed path uses (one
        shared compile-cache entry; cost_analysis describes the module
        that actually runs)."""
        return jitted.lower(place_state(state), place_tokens(tokens))

    step.lower = lower
    return step
