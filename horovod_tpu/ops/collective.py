"""Named-axis collective primitives over the device mesh.

The TPU-native replacement for the reference's op implementations
(``horovod/common/ops/mpi_operations.cc``, ``nccl_operations.cc``,
``gloo_operations.cc``): instead of library calls on raw buffers, each
collective is a JAX primitive bound to mesh axis names and compiled by XLA
into ICI/DCN collectives. Use these inside ``jax.shard_map`` (or any
named-axis context) — that is the compiled data plane. Called *outside* a
mesh context they fall back to an eager cross-process path (the analogue of
the reference's eager framework ops).

Reduction op surface mirrors ``horovod/torch/mpi_ops.py`` /
``horovod/common/message.h:46-49``: Sum, Average, Adasum (+ Min/Max
extensions).
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from horovod_tpu import compat
from horovod_tpu.diag import recorder as _flightrec
from horovod_tpu.ops.reduction import Adasum, Average, Max, Min, Sum
from horovod_tpu.parallel import mesh as mesh_lib
from horovod_tpu.telemetry import instruments as _tele
from horovod_tpu.telemetry import ledger as _ledger


def _eager_recorded(op_name, fn, x, nbytes, hash_shape=True):
    """Run the eager collective ``fn`` bracketed by flight-recorder
    entry/exit events: a rank that blocks (or dies) inside the call
    leaves a dangling entry naming the collective it is parked in —
    the post-mortem analogue of the reference stall inspector's
    per-tensor missing-ranks view (``stall_inspector.cc``). No recorder
    installed -> two no-op calls. ``hash_shape=False`` keeps the operand
    shape out of the desync digest for variable-length collectives.

    The host time spent here is EXPOSED collective time — unlike the
    compiled pipeline's collectives, nothing overlaps it — so it is
    charged to the goodput ledger's ``exposed_collective`` phase
    (trace-time dispatches never route through this funnel)."""
    seq = _flightrec.collective_enter(op_name, x, nbytes=nbytes,
                                      mode="eager", hash_shape=hash_shape)
    ok = False
    t0 = time.perf_counter()
    try:
        out = fn()
        ok = True
        return out
    finally:
        _ledger.get_ledger().charge("exposed_collective",
                                    time.perf_counter() - t0)
        _flightrec.collective_exit(op_name, seq, ok=ok)


def _wire_bytes(x):
    """Payload bytes of one collective operand (shape is static even for
    tracers, so this works at trace time)."""
    try:
        return int(np.prod(jnp.shape(x)) *
                   np.dtype(jnp.result_type(x)).itemsize)
    # hvd-lint: disable=HVD-EXCEPT -- byte accounting must never break a dispatch
    except Exception:
        return 0


def _resolve_axes(axes):
    if axes is None:
        return mesh_lib.data_axis_names()
    if isinstance(axes, str):
        return (axes,)
    return tuple(axes)


def _in_named_context(axes):
    """True when every axis in ``axes`` is bound (i.e. we are inside
    shard_map / a named-axis trace)."""
    bound = compat.bound_axis_names()
    if not bound:
        return False
    return all(a in bound for a in axes)


def mesh_size(axes=None):
    """Number of participants across ``axes`` (static)."""
    axes = _resolve_axes(axes)
    if _in_named_context(axes):
        return int(np.prod([lax.axis_size(a) for a in axes]))
    m = mesh_lib.get_mesh()
    shape = dict(zip(m.axis_names, m.devices.shape))
    return int(np.prod([shape[a] for a in axes]))


def mesh_rank(axes=None):
    """Linearized index of this shard across ``axes`` (row-major, matching
    mesh axis order). Only meaningful inside a named-axis context."""
    axes = _resolve_axes(axes)
    idx = jnp.zeros((), dtype=jnp.int32)
    for a in axes:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


def allreduce(x, op=Average, axes=None, compression=None,
              logical_nbytes=None):
    """Reduce ``x`` across all shards on ``axes``; every shard receives the
    result. Reference: ``MPIAllreduce``/``NCCLAllreduce``
    (``mpi_operations.cc``, ``nccl_operations.cc:55-105``).

    ``compression`` (see ``horovod_tpu.ops.compression``) casts to a narrow
    wire dtype before the collective, mirroring
    ``horovod/torch/compression.py``. Only REDUCIBLE wire formats (cast
    compressors — values may be summed at the wire dtype) are legal here:
    chunked quantizers (fp8/int8) carry per-chunk scales that cannot be
    summed in flight, so they must go through the exchange-then-reduce
    fusion pipeline (``fused_allreduce`` / the bucketed reduce-scatter
    path) — passing one raises instead of silently computing garbage.
    """
    if op not in (Sum, Average, Min, Max, Adasum):
        raise ValueError(f"unknown reduction op: {op!r}")
    if compression is not None and getattr(compression, "chunked", False):
        raise ValueError(
            f"{compression.name} is a chunked quantizer: its per-chunk "
            "scales cannot be summed on the wire, so a plain allreduce "
            "cannot carry it. Use hvd.fused_allreduce(...) or the bucketed "
            "pipeline (DistributedOptimizer(compression=...)), which "
            "exchange compressed chunks and reduce after decoding.")
    axes = _resolve_axes(axes)
    nbytes = _wire_bytes(x)
    if not _in_named_context(axes):
        _tele.record_collective("allreduce", nbytes,
                                logical_nbytes=logical_nbytes)
        return _eager_recorded("allreduce",
                               lambda: _eager_allreduce(x, op, axes),
                               x, nbytes)
    _flightrec.collective_enter("allreduce", x, nbytes=nbytes, mode="trace")
    if compression is not None:
        x, ctx = compression.compress(x)
        _tele.record_collective("allreduce", _wire_bytes(x),
                                logical_nbytes=nbytes)
    else:
        # logical_nbytes: a caller (fused_allreduce's cast path) that
        # narrowed the payload BEFORE this dispatch passes the
        # uncompressed width so the logical/wire ratio stays honest
        _tele.record_collective("allreduce", nbytes,
                                logical_nbytes=logical_nbytes)
    if op == Sum:
        out = lax.psum(x, axes)
    elif op == Average:
        out = lax.pmean(x, axes)
    elif op == Min:
        out = lax.pmin(x, axes)
    elif op == Max:
        out = lax.pmax(x, axes)
    elif op == Adasum:
        from horovod_tpu.ops import adasum as adasum_lib
        out = adasum_lib.adasum_allreduce(x, axes)
    else:
        raise ValueError(f"unknown reduction op: {op!r}")
    if compression is not None:
        out = compression.decompress(out, ctx)
    return out


def allgather(x, axes=None, tiled=True, logical_nbytes=None):
    """Concatenate ``x`` from all shards along dim 0 (reference:
    ``MPIAllgather`` / ``gloo::allgatherv``, ``mpi_operations.cc``).

    XLA collectives are static-shape, so all shards must contribute the same
    shape here; the variable-length (allgatherv) semantics of the reference
    live in the eager path, which pads to the negotiated max length.

    ``logical_nbytes`` overrides the uncompressed-byte accounting when the
    payload is already at a narrowed wire width (the compressed fusion
    pipeline passes the logical width of what it narrowed; 0 marks pure
    wire overhead like quantizer scales).
    """
    axes = _resolve_axes(axes)
    nbytes = _wire_bytes(x)
    _tele.record_collective("allgather", nbytes,
                            logical_nbytes=logical_nbytes)
    if not _in_named_context(axes):
        # hash_shape=False: the eager path carries allgatherv semantics
        # (per-rank first dims may differ by design), so the shape must
        # not enter the cross-rank schedule digest
        return _eager_recorded("allgather",
                               lambda: _eager_allgather(x, axes),
                               x, nbytes, hash_shape=False)
    _flightrec.collective_enter("allgather", x, nbytes=nbytes, mode="trace")
    out = x
    # Gather over the minor axis first so the result is ordered by
    # linearized mesh_rank (major axis varies slowest).
    for a in reversed(axes):
        out = lax.all_gather(out, a, axis=0, tiled=tiled)
    return out


def broadcast(x, root_rank=0, axes=None):
    """Every shard receives shard ``root_rank``'s value (reference:
    ``MPIBroadcast``, ``mpi_operations.cc``; TF op ``HorovodBroadcastOp``,
    ``tensorflow/mpi_ops.cc:411``).

    Implemented as masked psum — the same zero-fill trick the reference's
    Join path uses (``controller.cc:209-220``); XLA lowers it to a
    collective broadcast when the mask is a single rank.
    """
    axes = _resolve_axes(axes)
    nbytes = _wire_bytes(x)
    _tele.record_collective("broadcast", nbytes)
    if not _in_named_context(axes):
        return _eager_recorded("broadcast",
                               lambda: _eager_broadcast(x, root_rank, axes),
                               x, nbytes)
    _flightrec.collective_enter("broadcast", x, nbytes=nbytes, mode="trace")
    me = mesh_rank(axes)
    contrib = jnp.where(me == root_rank, x, jnp.zeros_like(x))
    return lax.psum(contrib, axes)


def reducescatter(x, op=Sum, axes=None, logical_nbytes=None):
    """Reduce across shards and scatter the result: each shard gets a
    1/size slice along dim 0. Internal building block in the reference's
    hierarchical path (``nccl_operations.cc:198-248``), exposed here as a
    first-class op (it is the bandwidth-optimal half of an allreduce).

    Chunk ``i`` of dim 0 lands on the shard whose ``mesh_rank(axes)`` is
    ``i`` — the same linearized ordering every other collective uses, and
    the inverse of :func:`allgather` (``allgather(reducescatter(x))``
    round-trips when the reduction is a no-op). ``logical_nbytes``: see
    :func:`allgather`."""
    axes = _resolve_axes(axes)
    if op not in (Sum, Average):
        raise ValueError("reducescatter supports Sum or Average")
    nbytes = _wire_bytes(x)
    _tele.record_collective("reducescatter", nbytes,
                            logical_nbytes=logical_nbytes)
    if not _in_named_context(axes):
        return _eager_recorded("reducescatter",
                               lambda: _eager_reducescatter(x, op, axes),
                               x, nbytes)
    _flightrec.collective_enter("reducescatter", x, nbytes=nbytes,
                                mode="trace")
    out = x
    for a in axes:
        out = lax.psum_scatter(out, a, scatter_dimension=0, tiled=True)
    if op == Average:
        out = out / mesh_size(axes)
    return out


def alltoall(x, axes=None, logical_nbytes=None):
    """Split dim 0 into size chunks, exchange chunk i with shard i, concat
    along dim 0. (Not in Horovod 0.18.2 — added for the sequence-parallel /
    Ulysses path; Horovod grew hvd.alltoall later.)

    Multiple axes are treated as ONE linearized participant set, major
    axis slowest — chunk i goes to the shard whose ``mesh_rank`` is i,
    matching every other collective's rank ordering. ``logical_nbytes``:
    see :func:`allgather`."""
    axes = _resolve_axes(axes)
    nbytes = _wire_bytes(x)
    _tele.record_collective("alltoall", nbytes,
                            logical_nbytes=logical_nbytes)
    if not _in_named_context(axes):
        return _eager_recorded("alltoall",
                               lambda: _eager_alltoall(x, axes),
                               x, nbytes)
    _flightrec.collective_enter("alltoall", x, nbytes=nbytes, mode="trace")
    return lax.all_to_all(x, axes, split_axis=0, concat_axis=0, tiled=True)


# ---------------------------------------------------------------------------
# Eager cross-process path.
#
# The compiled path above covers everything inside a step function. These
# run when the user calls hvd.allreduce(...) at top level with a local array
# (the reference's eager op path, e.g. horovod/torch/mpi_ops.py
# allreduce_async + synchronize). With one launched process they are local
# no-ops by Horovod semantics (world size 1). Under hvdrun, the native core
# (TCP ring collectives, horovod_tpu._core) carries them; in a
# jax.distributed job without the core, a compiled global reduction over
# the process mesh does.
# ---------------------------------------------------------------------------

_EAGER_COUNTERS = {}


def _eager_name(kind):
    n = _EAGER_COUNTERS.get(kind, 0)
    _EAGER_COUNTERS[kind] = n + 1
    return f"eager.{kind}.{n}"


def _native_core():
    from horovod_tpu import _core
    if _core.is_initialized():
        return _core
    return None


def _num_processes():
    return jax.process_count()


@functools.lru_cache(maxsize=None)
def _proc_mesh():
    devs = np.asarray(jax.devices())
    return jax.sharding.Mesh(devs.reshape(devs.size), ("proc",))


def invalidate_proc_mesh():
    """Drop the cached eager-path process mesh. Must be called whenever
    the global device set can change (``basics.shutdown()``, elastic
    re-rendezvous): a staged eager collective on a mesh built from the
    OLD ``jax.devices()`` would address departed devices."""
    _proc_mesh.cache_clear()


def _stage_global(x):
    """Build a global array of shape (ndev, *x.shape) whose shard d is this
    process's local value (replicated over its local devices)."""
    x = jnp.asarray(x)
    m = _proc_mesh()
    local = [jax.device_put(x[None], d) for d in jax.local_devices()]
    sharding = jax.sharding.NamedSharding(
        m, jax.sharding.PartitionSpec("proc"))
    gshape = (len(jax.devices()),) + x.shape
    return jax.make_array_from_single_device_arrays(gshape, sharding, local)


def _assert_contiguous_process_layout(devices, nldev):
    """The staged eager Adasum tree is only correct when the global
    device order is nldev-aligned and process-contiguous (device ``i``
    owned by process ``i // nldev``): the first log2(nldev) tree levels
    then pair each process's replicated copies with themselves. A
    non-contiguous enumeration would adasum copies from DIFFERENT
    processes at those levels and silently corrupt the result (ADVICE
    round 5) — so refuse loudly instead."""
    bad = [(i, d) for i, d in enumerate(devices)
           if getattr(d, "process_index", 0) != i // nldev]
    if bad:
        i, d = bad[0]
        raise RuntimeError(
            "eager Adasum requires a contiguous nldev-aligned device "
            f"layout (device index // {nldev} == process_index); device "
            f"{i} ({d}) belongs to process "
            f"{getattr(d, 'process_index', 0)}, expected {i // nldev}. "
            "Use the compiled (shard_map) Adasum path, or launch with a "
            "process-contiguous device order.")


def _eager_allreduce(x, op, axes):
    del axes
    core = _native_core()
    if core is not None:
        return jnp.asarray(core.allreduce(np.asarray(x),
                                          _eager_name("allreduce"), op=op))
    nproc = _num_processes()
    if nproc == 1:
        return jnp.asarray(x)
    g = _stage_global(x)
    nldev = len(jax.local_devices())

    if op == Adasum:
        # Staged XOR-tree over the proc mesh. Each process's value sits
        # replicated on its nldev local devices; since adasum(v, v) = v,
        # the first log2(nldev) tree levels collapse the duplicates and
        # the remaining levels perform the true cross-process Adasum —
        # so running the tree over ALL devices gives exactly the
        # per-process result (both counts must be powers of 2, the
        # reference's own Adasum constraint).
        from horovod_tpu.ops import adasum as adasum_lib
        _assert_contiguous_process_layout(jax.devices(), nldev)
        ndev = len(jax.devices())
        if (ndev & (ndev - 1)) or (nldev & (nldev - 1)):
            raise ValueError(
                "eager Adasum requires power-of-2 process and "
                f"local-device counts (got {nproc} x {nldev})")
        m = _proc_mesh()
        spec = jax.sharding.PartitionSpec("proc")
        f = jax.jit(jax.shard_map(
            lambda t: adasum_lib.adasum_allreduce(t[0], ("proc",)),
            mesh=m, in_specs=(spec,),
            out_specs=jax.sharding.PartitionSpec(), check_vma=False))
        return jax.device_get(f(g))

    @jax.jit
    def _reduce(g):
        if op in (Sum, Average):
            s = jnp.sum(g, axis=0) / nldev
            return s / nproc if op == Average else s
        if op == Min:
            return jnp.min(g, axis=0)
        if op == Max:
            return jnp.max(g, axis=0)
        raise ValueError(f"unsupported eager reduction: {op!r}")

    out = _reduce(g)
    return jax.device_get(out)


def _eager_allgather(x, axes):
    del axes
    core = _native_core()
    if core is not None:
        return jnp.asarray(core.allgather(np.asarray(x),
                                          _eager_name("allgather")))
    nproc = _num_processes()
    if nproc == 1:
        return jnp.asarray(x)
    g = _stage_global(x)
    nldev = len(jax.local_devices())

    @jax.jit
    def _gather(g):
        # one contribution per process: take its first local device's copy
        return g[::nldev].reshape((-1,) + g.shape[2:])

    return jax.device_get(_gather(g))


def _eager_reducescatter(x, op, axes):
    """Eager cross-process reduce-scatter (the one collective that had no
    eager fallback — calling it outside a named context used to die inside
    ``lax.psum_scatter``). Same routing as its siblings: native core when
    live, staged proc-mesh reduction otherwise, local no-op at world 1."""
    del axes
    core = _native_core()
    if core is not None:
        return jnp.asarray(core.reducescatter(np.asarray(x),
                                              _eager_name("reducescatter"),
                                              op=op))
    nproc = _num_processes()
    if nproc == 1:
        return jnp.asarray(x)
    x = jnp.asarray(x)
    if x.ndim == 0:
        raise ValueError("reducescatter needs at least 1 dimension to "
                         "scatter over")
    g = _stage_global(x)
    nldev = len(jax.local_devices())
    m = _proc_mesh()

    # SPMD rule (same shape-asymmetry handling as _eager_alltoall): all
    # processes compute the full reduction replicated, then each slices
    # its own rows on the host. Remainder rows go to the first ranks,
    # matching the native core's split (_core.reducescatter_async).
    @functools.partial(
        jax.jit, out_shardings=jax.sharding.NamedSharding(
            m, jax.sharding.PartitionSpec()))
    def _reduce(g):
        s = jnp.sum(g, axis=0) / nldev  # one contribution per process
        return s / nproc if op == Average else s

    full = jax.device_get(_reduce(g))
    me = jax.process_index()
    base, rem = divmod(x.shape[0], nproc)
    start = me * base + min(me, rem)
    rows = base + (1 if me < rem else 0)
    return jnp.asarray(full[start:start + rows])


def _eager_alltoall(x, axes):
    del axes
    core = _native_core()
    if core is not None:
        return jnp.asarray(core.alltoall(np.asarray(x),
                                         _eager_name("alltoall")))
    nproc = _num_processes()
    if nproc == 1:
        return jnp.asarray(x)
    x = jnp.asarray(x)
    if x.shape[0] % nproc:
        raise ValueError(
            f"alltoall dim 0 ({x.shape[0]}) must divide by the process "
            f"count ({nproc})")
    g = _stage_global(x)
    nldev = len(jax.local_devices())
    m = _proc_mesh()

    # SPMD rule: every process runs the IDENTICAL program (no
    # process_index inside the trace). All processes compute the full
    # [P, P, chunk] exchange replicated, then each selects its column on
    # the host — same shape asymmetry handling as _eager_broadcast.
    @functools.partial(
        jax.jit, out_shardings=jax.sharding.NamedSharding(
            m, jax.sharding.PartitionSpec()))
    def _exchange(g):
        h = g[::nldev]  # one contribution per process: [P, n, ...]
        return h.reshape((nproc, nproc, h.shape[1] // nproc) + h.shape[2:])

    chunks = jax.device_get(_exchange(g))
    me = jax.process_index()
    return jnp.asarray(chunks[:, me].reshape((x.shape[0],) + x.shape[1:]))


def _eager_broadcast(x, root_rank, axes):
    del axes
    core = _native_core()
    if core is not None:
        return jnp.asarray(core.broadcast(np.asarray(x),
                                          _eager_name("broadcast"),
                                          root_rank=root_rank))
    nproc = _num_processes()
    if nproc == 1:
        return jnp.asarray(x)
    gathered = _eager_allgather(x[None] if jnp.ndim(x) == 0 else
                                jnp.asarray(x)[None], None)
    return jax.device_get(jnp.asarray(gathered)[root_rank])
