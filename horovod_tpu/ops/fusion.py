"""Static tensor fusion: pack many small tensors into few big collectives.

The reference fuses at runtime: a background thread packs ready tensors into
a 64 MB fusion buffer each cycle and launches one collective per fused batch
(``FuseResponses``, ``horovod/common/controller.cc:639-769``;
``MemcpyInFusionBuffer``, ``horovod/common/ops/collective_operations.cc``).
That design fights XLA: a different fused set each step means a different
collective shape and a recompile.

The TPU-native design fuses **statically at trace time**: the gradient
pytree is flattened, leaves are grouped by dtype and packed in traversal
order into flat buckets of at most ``fusion_threshold`` bytes (default 64 MB,
matching ``operations.cc:403``), one collective is emitted per bucket, and
XLA sees the same shapes every step — compile once, zero renegotiation.
This is strictly stronger than the reference's steady-state response-cache
path (``response_cache.h:45-102``): the "cache hit" is baked into the
executable.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np

from horovod_tpu.ops import collective


@dataclasses.dataclass(frozen=True)
class _Bucket:
    """One fusion buffer: which flat leaves it packs and where."""
    dtype: object
    leaf_indices: tuple  # indices into the flattened leaf list
    sizes: tuple         # element count per packed leaf
    shapes: tuple        # original shape per packed leaf


def plan_buckets(leaves, threshold_bytes, reverse=False):
    """Greedy packing of leaves into dtype-homogeneous buckets of at most
    ``threshold_bytes`` (a single leaf larger than the threshold gets its own
    bucket, like a single tensor larger than the reference's fusion buffer,
    ``controller.cc:687-696``).

    ``reverse=True`` packs in REVERSE traversal order: backprop produces
    gradients for the last layers first, so reverse-ordered buckets fill in
    the order they become ready — the ordering the overlapped reduce-scatter
    pipeline (``bucket_schedule``) wants, and the same trick the reference's
    bucketed DDP implementations use (gradient hooks fire back-to-front)."""
    by_dtype = {}
    order = range(len(leaves) - 1, -1, -1) if reverse else range(len(leaves))
    for i in order:
        by_dtype.setdefault(jnp.asarray(leaves[i]).dtype, []).append(i)
    buckets = []
    for dtype, idxs in by_dtype.items():
        itemsize = np.dtype(dtype).itemsize
        cur, cur_bytes = [], 0
        for i in idxs:
            nbytes = int(np.prod(np.shape(leaves[i]))) * itemsize
            if cur and cur_bytes + nbytes > threshold_bytes:
                buckets.append(_make_bucket(dtype, cur, leaves))
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            buckets.append(_make_bucket(dtype, cur, leaves))
    return buckets


def _make_bucket(dtype, idxs, leaves):
    return _Bucket(
        dtype=dtype,
        leaf_indices=tuple(idxs),
        sizes=tuple(int(np.prod(np.shape(leaves[i])) or 1) for i in idxs),
        shapes=tuple(tuple(np.shape(leaves[i])) for i in idxs),
    )


def _pack(bucket, leaves):
    return jnp.concatenate(
        [jnp.ravel(leaves[i]) for i in bucket.leaf_indices])


def _unpack(bucket, flat):
    out = {}
    offset = 0
    for i, size, shape in zip(bucket.leaf_indices, bucket.sizes,
                              bucket.shapes):
        out[i] = flat[offset:offset + size].reshape(shape)
        offset += size
    return out


# ---------------------------------------------------------------------------
# Bucketed reduce-scatter pipeline.
#
# The overlapped gradient-exchange data plane: instead of one fused
# allreduce after the full backward, gradients are packed into
# reverse-traversal-ordered buckets and each bucket is reduce-scattered as
# soon as it is ready, so the next microbatch's backward overlaps the
# previous bucket's reduction (XLA's async-collective/latency-hiding
# scheduler does the actual overlapping — config.xla_overlap_flags). The
# reduced 1/world shards feed either an all-gather (plain data-parallel) or
# a ZeRO-1 sharded optimizer update (parallel/zero.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class BucketSchedule:
    """Static plan for the pipelined bucket exchange.

    ``axes`` is the SCATTER ORDER: reduce-scatter walks it first-to-last,
    all-gather inverts it, and the shard owned by a rank is chunk
    ``collective.mesh_rank(axes)`` — so a consistent schedule is the single
    source of truth for which rank owns which flat range (the contract
    ``parallel/zero.py`` builds its optimizer-state partition on).
    ``padded_sizes`` rounds each bucket up to a multiple of ``world`` so
    XLA's equal-shard constraint holds for any parameter count."""

    buckets: tuple       # _Bucket, reverse-traversal (backward-ready) order
    padded_sizes: tuple  # per-bucket element count, multiple of world
    world: int
    axes: tuple

    @property
    def shard_sizes(self):
        return tuple(p // self.world for p in self.padded_sizes)


def bucket_schedule(leaves, world, threshold_bytes=None, axes=None,
                    hierarchical=False):
    """Plan the bucketed exchange for ``leaves`` (one bucket set, reused by
    every microbatch and every step — compile once).

    With ``hierarchical`` and a dcn axis present, the scatter order is
    reordered ICI-first so the DCN stage moves ``1/ici_size`` of the bytes
    (the two-level composition of ``parallel/hierarchical``)."""
    from horovod_tpu import basics
    from horovod_tpu.config import DEFAULT_FUSION_THRESHOLD
    from horovod_tpu.parallel.mesh import DCN_AXIS

    if threshold_bytes is None:
        cfg = basics._state.config
        threshold_bytes = (cfg.fusion_threshold if cfg is not None
                           else DEFAULT_FUSION_THRESHOLD)
    axes = collective._resolve_axes(axes)
    if hierarchical and DCN_AXIS in axes and len(axes) > 1:
        axes = tuple(a for a in axes if a != DCN_AXIS) + (DCN_AXIS,)
    buckets = tuple(plan_buckets(leaves, threshold_bytes, reverse=True))
    padded = tuple(sum(b.sizes) + (-sum(b.sizes)) % world for b in buckets)
    return BucketSchedule(buckets=buckets, padded_sizes=padded,
                          world=world, axes=axes)


def _timeline_mark(kind, idx, nbytes):
    """BUCKET_RS / BUCKET_AG instant markers: emitted at trace time (the
    pipeline is compiled, so per-step device timing lives in the XLA
    profiler; these markers document the emitted schedule next to it).
    When a step-dispatch flow is open (``training.make_train_step``
    stashes its id on the timeline), the marker joins it — linking the
    dispatch slice to the bucket collectives it scheduled."""
    from horovod_tpu import basics
    from horovod_tpu.diag import recorder as _flightrec
    _flightrec.record_event("bucket", kind=kind, idx=idx, nbytes=nbytes)
    tl = basics._state.timeline
    if tl is not None:
        tl.bucket_marker(kind, idx, nbytes,
                         flow_id=getattr(tl, "_step_flow_id", None))


def _bucket_fill(schedule, idx):
    used = sum(schedule.buckets[idx].sizes)
    padded = schedule.padded_sizes[idx]
    return used / padded if padded else 1.0


def _pack_padded(schedule, idx, leaves):
    """Bucket ``idx`` packed flat and zero-padded to its scheduled size."""
    flat = _pack(schedule.buckets[idx], leaves)
    pad = schedule.padded_sizes[idx] - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def reduce_scatter_bucket(schedule, idx, leaves, op=collective.Average):
    """Pack bucket ``idx`` from ``leaves``, pad to the schedule's padded
    size, and reduce-scatter it over the schedule's scatter order. Returns
    this rank's reduced shard (``shard_sizes[idx]`` elements)."""
    from horovod_tpu import telemetry

    t0 = time.perf_counter()
    flat = _pack_padded(schedule, idx, leaves)
    nbytes = flat.shape[0] * flat.dtype.itemsize
    _timeline_mark("RS", idx, nbytes)
    out = collective.reducescatter(flat, op=op, axes=schedule.axes)
    telemetry.record_bucket("rs", _bucket_fill(schedule, idx), nbytes,
                            dispatch_s=time.perf_counter() - t0,
                            dtype=flat.dtype)
    return out


def reduce_scatter_bucket_compressed(schedule, idx, leaves, wire,
                                     op=collective.Average, residual=None):
    """Wire-compressed :func:`reduce_scatter_bucket`: the interconnect
    carries bucket ``idx`` at ``wire``'s width instead of the gradient
    dtype. Returns ``(shard, new_residual)``.

    * **Cast wire** (bf16/fp16): sums of cast values are meaningful, so
      the bucket is narrowed and reduce-scattered AT the wire dtype —
      same collective as the exact path, half the bytes.
    * **Chunked quantizer** (fp8/int8): per-chunk scales cannot be summed
      in flight, so the exchange is an all-to-all of the quantized
      ``[world, shard]`` rows (each rank receives every peer's
      contribution to ITS shard, still at wire width — the same
      bandwidth-optimal volume as a ring reduce-scatter) followed by a
      local decode-and-sum in fp32. Chunks never straddle the shard
      boundary, so each destination decodes its rows from the scales that
      rode with them.

    ``residual`` is the per-bucket error-feedback carry: it is added into
    the bucket BEFORE compression and the new quantization error
    (``values - decode(encode(values))``) comes back as ``new_residual``
    — the caller threads it into the next step (``training.
    make_train_step``). Pass ``residual=None`` for stateless compression
    (``new_residual`` is then None too). Non-float buckets are never
    narrowed: they take the exact path bit-for-bit and pass the residual
    through unchanged."""
    from horovod_tpu import telemetry

    if not jnp.issubdtype(schedule.buckets[idx].dtype, jnp.floating):
        # decide off the bucket's static dtype BEFORE packing — the
        # delegate re-packs, so packing here would trace the bucket twice
        return reduce_scatter_bucket(schedule, idx, leaves, op=op), residual
    t0 = time.perf_counter()
    flat = _pack_padded(schedule, idx, leaves)
    logical_nbytes = flat.shape[0] * flat.dtype.itemsize
    grad_dtype = flat.dtype
    world = schedule.world
    shard = schedule.shard_sizes[idx]
    if residual is not None:
        # the compensated sum and the residual math stay in fp32: for
        # bf16 gradients the quantization error sits at or below the
        # bf16 ulp, so adding the carry AT the gradient dtype would
        # round the compensation away and EF would silently degrade to
        # stateless quantization
        flat = flat.astype(jnp.float32) + residual.reshape(flat.shape)
    if getattr(wire, "chunked", False):
        q = wire.for_length(shard)
        rows = flat.reshape(world, shard)
        if residual is not None:
            wire_rows, scales, deq = q.roundtrip(rows)
            new_residual = (rows - deq).reshape(flat.shape)
        else:
            wire_rows, scales = q.compress_flat(rows)
            new_residual = None
        # per-ROW accounting: each of the world rows pads to a chunk
        # multiple and carries its own scales (chunks never straddle the
        # shard boundary), so the wire volume is world x the per-shard
        # cost, not one flat-bucket encode
        nbytes = q.wire_bytes(shard, grad_dtype) * world
        _timeline_mark("RS", idx, nbytes)
        # row r of the received array is rank r's quantized contribution
        # to THIS rank's shard (alltoall concatenates in linearized
        # mesh_rank order — the same ownership contract reducescatter
        # uses, pinned by tests/test_compression.py). The payload's
        # logical width is the full fp-width bucket; the scales are pure
        # wire overhead (logical 0), so the per-op wire/logical counters
        # stay consistent with the bucket-level aggregate.
        recv_rows = collective.alltoall(wire_rows, axes=schedule.axes,
                                        logical_nbytes=logical_nbytes)
        recv_scales = collective.alltoall(scales, axes=schedule.axes,
                                          logical_nbytes=0)
        vals = q.decompress_flat(recv_rows, recv_scales, jnp.float32,
                                 n=shard)
        out = jnp.sum(vals, axis=0)
        if op == collective.Average:
            out = out / world
        out = out.astype(grad_dtype)
    else:
        if residual is not None:
            wire_flat, _, deq = wire.roundtrip(flat)
            new_residual = flat - deq
        else:
            wire_flat, _ = wire.compress_flat(flat)
            new_residual = None
        nbytes = wire.wire_bytes(flat.shape[0], grad_dtype)
        _timeline_mark("RS", idx, nbytes)
        out = collective.reducescatter(
            wire_flat, op=op, axes=schedule.axes,
            logical_nbytes=logical_nbytes).astype(grad_dtype)
    telemetry.record_bucket("rs", _bucket_fill(schedule, idx), nbytes,
                            dispatch_s=time.perf_counter() - t0,
                            logical_nbytes=logical_nbytes,
                            dtype=grad_dtype)
    return out, new_residual


def all_gather_bucket(schedule, idx, shard):
    """Inverse of :func:`reduce_scatter_bucket`: all-gather the per-rank
    shards of bucket ``idx`` back into the full (padded) flat bucket.
    ``collective.allgather`` walks the axes last-to-first, which inverts
    the scatter order, so chunk ownership round-trips exactly."""
    from horovod_tpu import telemetry

    t0 = time.perf_counter()
    nbytes = shard.shape[0] * schedule.world * shard.dtype.itemsize
    _timeline_mark("AG", idx, nbytes)
    out = collective.allgather(shard, axes=schedule.axes)
    telemetry.record_bucket("ag", _bucket_fill(schedule, idx), nbytes,
                            dispatch_s=time.perf_counter() - t0,
                            dtype=shard.dtype)
    return out


def all_gather_bucket_compressed(schedule, idx, shard_vals, wire,
                                 residual=None):
    """Wire-compressed :func:`all_gather_bucket`: each rank narrows ITS
    shard of bucket ``idx`` (cast, or chunked-quantize with per-chunk
    scales riding along), all-gathers the wire payload, and decodes every
    peer's rows back to the full padded flat bucket. Returns
    ``(flat, new_residual)``.

    ``residual`` is the all-gather half's error-feedback carry (shard-
    sized — only this rank's own shard is ever encoded here): added in
    before compression, quantization error returned as ``new_residual``.
    In the ZeRO-1 pipeline the gathered payload is the parameter DELTA,
    so this is delta-EF (DoubleSqueeze-style two-way compensation): every
    rank applies the same decoded delta — params stay replicated-
    consistent — and the residual makes the CUMULATIVE applied delta
    track the exact one. Non-float shards take the exact path."""
    from horovod_tpu import telemetry

    t0 = time.perf_counter()
    if not jnp.issubdtype(shard_vals.dtype, jnp.floating):
        return all_gather_bucket(schedule, idx, shard_vals), residual
    world = schedule.world
    shard = schedule.shard_sizes[idx]
    logical_nbytes = shard * world * shard_vals.dtype.itemsize
    out_dtype = shard_vals.dtype
    x = shard_vals
    if residual is not None:
        # fp32 compensation math — see reduce_scatter_bucket_compressed
        x = x.astype(jnp.float32) + residual.reshape(x.shape)
    if getattr(wire, "chunked", False):
        q = wire.for_length(shard)
        if residual is not None:
            wire_shard, scales, deq = q.roundtrip(x)
            new_residual = x - deq
        else:
            wire_shard, scales = q.compress_flat(x)
            new_residual = None
        nbytes = q.wire_bytes(shard, out_dtype) * world
        _timeline_mark("AG", idx, nbytes)
        # allgather's own counter uses input-shard bytes; its logical
        # counterpart is this rank's shard at the logical dtype
        gathered = collective.allgather(
            wire_shard, axes=schedule.axes,
            logical_nbytes=shard * out_dtype.itemsize)
        g_scales = collective.allgather(scales, axes=schedule.axes,
                                        logical_nbytes=0)
        flat = q.decompress_flat(
            gathered.reshape(world, -1), g_scales.reshape(world, -1),
            out_dtype, n=shard).reshape(world * shard)
    else:
        if residual is not None:
            wire_shard, _, deq = wire.roundtrip(x)
            new_residual = x - deq
        else:
            wire_shard, _ = wire.compress_flat(x)
            new_residual = None
        nbytes = wire.wire_bytes(shard, out_dtype) * world
        _timeline_mark("AG", idx, nbytes)
        flat = collective.allgather(
            wire_shard, axes=schedule.axes,
            logical_nbytes=shard * out_dtype.itemsize
            ).astype(out_dtype)
    telemetry.record_bucket("ag", _bucket_fill(schedule, idx), nbytes,
                            dispatch_s=time.perf_counter() - t0,
                            logical_nbytes=logical_nbytes,
                            dtype=shard_vals.dtype)
    return flat, new_residual


def unpack_bucket(schedule, idx, flat, leaves):
    """Scatter the flat bucket back into leaf positions: returns
    ``{leaf_index: array}`` with each array cast to its leaf's dtype
    (padding tail ignored)."""
    out = {}
    for i, arr in _unpack(schedule.buckets[idx], flat).items():
        out[i] = arr.astype(jnp.asarray(leaves[i]).dtype)
    return out


def fused_allreduce(tree, op=collective.Average, axes=None,
                    compression=None, threshold_bytes=None,
                    hierarchical=None):
    """Allreduce every leaf of ``tree`` using fused flat buckets.

    This is the gradient hot path — the TPU equivalent of the reference's
    fuse → collective → unfuse cycle (``PerformOperation``,
    ``operations.cc:227-304``), fully compiled.

    ``hierarchical`` forces the two-level ICI x DCN reduction (reference:
    ``NCCLHierarchicalAllreduce``, ``nccl_operations.cc:150-346``); default
    auto-enables it when the mesh has a dcn axis and config asks for it.

    ``compression`` may be a compressor object or a wire-dtype name
    (``"bf16"``/``"fp8_e4m3"``/``"int8"`` — ``compression.by_name``).
    Cast compressors narrow in place and reduce at the wire dtype;
    chunked quantizers (fp8/int8) are routed per float bucket through the
    bandwidth-optimal compressed reduce-scatter + all-gather pair
    (STATELESS here — no error feedback; the training pipeline carries
    the per-bucket residual). Chunked wire only composes with
    Sum/Average (Adasum/Min/Max have no exchange-then-reduce form — a
    loud error, not silent fallback); non-float buckets always take the
    exact path. Chunked wire is also SINGLE-LEVEL: ``hierarchical`` is
    ignored for it (with a warning when it would have applied) — the
    two-level ICI/DCN reduction has no compressed form, the DCN simply
    carries the narrowed volume.
    """
    from horovod_tpu import basics
    from horovod_tpu.config import DEFAULT_FUSION_THRESHOLD
    from horovod_tpu.ops import compression as compression_lib
    from horovod_tpu.parallel import hierarchical as hier_lib
    from horovod_tpu.parallel.mesh import DCN_AXIS

    if threshold_bytes is None:
        cfg = basics._state.config
        threshold_bytes = (cfg.fusion_threshold if cfg is not None
                           else DEFAULT_FUSION_THRESHOLD)
    if hierarchical is None:
        cfg = basics._state.config
        hierarchical = cfg.hierarchical_allreduce if cfg is not None else False
    if isinstance(compression, str):
        compression = compression_lib.by_name(compression)

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        return tree
    axes = collective._resolve_axes(axes)
    buckets = plan_buckets(leaves, threshold_bytes)

    chunked = compression is not None and getattr(compression, "chunked",
                                                  False)
    if chunked:
        if op not in (collective.Sum, collective.Average):
            raise ValueError(
                f"chunked wire format {compression.name!r} only composes "
                f"with Sum/Average (got {op!r}): Adasum/Min/Max reductions "
                "have no exchange-then-reduce form")
        try:
            world = collective.mesh_size(axes)
        except Exception:
            raise ValueError(
                "chunked wire compression needs the compiled mesh path "
                "(hvd.init() / shard_map); no mesh is available") from None
        if world == 1:
            compression, chunked = None, False  # no wire to compress
        elif hierarchical and DCN_AXIS in axes and len(axes) > 1:
            # the chunked exchange is a single-level all-to-all: there is
            # no two-level compressed composition (decoded partial sums
            # cannot be re-quantized without a second error budget), so
            # the DCN hop carries full per-rank wire volume — at 1/4
            # width. Say so instead of silently eating the knob.
            import warnings
            warnings.warn(
                f"hierarchical allreduce is ignored for the chunked wire "
                f"format {compression.name!r}: the quantized exchange is "
                "single-level, so the dcn axis carries the (narrowed) "
                "per-rank volume without the ICI-first reduction. Use "
                "bf16 cast compression if the two-level path matters "
                "more than the 4x narrowing (docs/PERFORMANCE.md).",
                stacklevel=2)

    new_leaves = [None] * len(leaves)
    for bucket in buckets:
        if chunked and jnp.issubdtype(bucket.dtype, jnp.floating):
            size = sum(bucket.sizes)
            sched1 = BucketSchedule(
                buckets=(bucket,), padded_sizes=(size + (-size) % world,),
                world=world, axes=axes)
            shard, _ = reduce_scatter_bucket_compressed(
                sched1, 0, leaves, compression, op=op)
            flat, _ = all_gather_bucket_compressed(sched1, 0, shard,
                                                   compression)
            for i, arr in _unpack(bucket, flat).items():
                new_leaves[i] = arr.astype(jnp.asarray(leaves[i]).dtype)
            continue
        flat = _pack(bucket, leaves)
        logical = flat.shape[0] * flat.dtype.itemsize
        if compression is not None:
            flat, ctx = compression.compress(flat)
        # the RS->AR->AG hierarchy only exists for sum/average; every
        # other op falls through to collective.allreduce, which computes
        # Min/Max flat and already runs Adasum's OWN 2-level composite
        # on a multi-axis mesh (ops/adasum.py) — one dispatch copy
        if (hierarchical and op in (collective.Sum, collective.Average)
                and DCN_AXIS in axes and len(axes) > 1):
            from horovod_tpu import telemetry

            # hierarchical_allreduce composes raw lax collectives that
            # record nothing themselves — account the dispatch here so a
            # cast-compressed payload keeps its wire-vs-logical
            # attribution on this path too
            telemetry.record_collective(
                "hier_allreduce", flat.shape[0] * flat.dtype.itemsize,
                logical_nbytes=logical)
            ici_axes = tuple(a for a in axes if a != DCN_AXIS)
            flat = hier_lib.hierarchical_allreduce(
                flat, ici_axes=ici_axes, dcn_axis=DCN_AXIS, op=op)
        else:
            flat = collective.allreduce(
                flat, op=op, axes=axes,
                logical_nbytes=(logical if compression is not None
                                else None))
        if compression is not None:
            flat = compression.decompress(flat, ctx)
        for i, arr in _unpack(bucket, flat).items():
            new_leaves[i] = arr.astype(jnp.asarray(leaves[i]).dtype)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


class AutotuneTimings(dict):
    """``{threshold_bytes: seconds}`` from :func:`autotune_fusion_threshold`
    plus ``retried`` — how many candidate trials hit an inverted slope
    window and entered the escalation loop (a nonzero count means the
    trial lengths were near the noise floor for this workload) —
    ``slope_window_escalations`` — how many 4x iter escalations those
    retries burned in total (0 with every trial cleanly measured; the
    BENCH json records it so a threshold that was MEASURED is
    distinguishable from one that was still a guessed upper bound after
    escalation) — and ``abstain_reason``: when the tuner returned
    ``(None, timings)`` instead of a winner, the human-readable reason
    why the trials carried no rankable signal (docs/AUTOTUNE.md, "When
    the tuner abstains")."""

    def __init__(self, *args, retried=0, slope_window_escalations=0,
                 abstain_reason=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.retried = retried
        self.slope_window_escalations = slope_window_escalations
        self.abstain_reason = abstain_reason


def autotune_fusion_threshold(tree, op=collective.Average, axes=None,
                              candidates=None, trials=10, apply=True,
                              tolerance=0.10, wire_candidates=None):
    """Pick the fusion bucket threshold by timed trials at init.

    The compiled-path analogue of the reference autotuner's
    fusion-threshold search (``parameter_manager.h:186-220``): on TPU the
    fused set is static per executable, so instead of online Bayesian
    optimization over cycles, we compile one executable per candidate
    threshold, time the fused allreduce of the actual gradient pytree on
    the real mesh, and keep the fastest. With ``apply=True`` (default)
    the winner becomes the process-wide default ``fusion_threshold`` used
    by ``fused_allreduce`` / ``DistributedOptimizer``.

    Timing uses the shared readback-slope primitive
    (``utils.benchmarks.slope_window``) — ``jax.block_until_ready`` does
    not synchronize through an async execution tunnel, and a repeated
    pure call on identical inputs can be memoized, so each trial call
    threads an incrementing ``salt`` operand and the evolving output
    back in as the next input (BENCH_NOTES.md, "Round-4 correction").

    Returns ``(best_threshold_bytes, timings)`` where ``timings`` is an
    :class:`AutotuneTimings` — ``{threshold: seconds for ``trials`` iters}``
    whose ``retried`` attribute counts the trials that hit an inverted
    slope window and were re-run with doubled iters (ranking candidates on
    an inverted window's full-window upper bound would compare fixed
    dispatch costs, not bucket plans — BENCH_r05 tail, VERDICT r5 #2).

    **Abstention (no-signal contract, docs/AUTOTUNE.md):** the tuner
    returns ``(None, timings)`` — installing nothing, with
    ``timings.abstain_reason`` set — instead of publishing a fake winner
    when the trials cannot rank candidates:

    * the world size over ``axes`` is 1 (the collectives are no-ops;
      every "timing" is pure dispatch noise), or
    * after retries some candidate is still an unresolved upper BOUND
      (``WindowTime.upper_bound``) within ``tolerance`` of the argmin —
      its true time could be anywhere at or below the bound, so the
      argmin is not trustworthy.

    **Wire-dtype axis:** with ``wire_candidates`` (a list of wire-format
    names — ``["none", "bf16", "fp8_e4m3", "int8"]``) the search grid
    becomes the cross product ``(threshold, wire)`` — the wire format a
    bucket should ride at depends on the bucket size the threshold
    produces (small buckets are dispatch-bound and gain nothing from
    narrowing; big ones are bandwidth-bound), so the two knobs must be
    ranked jointly, not in sequence. Timings are then keyed by the
    ``(threshold_bytes, wire_name)`` pair, the SAME cross-rank
    flag-allreduce and abstention machinery applies to the flattened
    grid, and ``apply=True`` installs BOTH ``config.fusion_threshold``
    and ``config.wire_dtype``. Returns ``((threshold, wire), timings)``
    in this mode. Note the trials rank wall-clock only — the wire
    formats differ in NUMERICS too (docs/PERFORMANCE.md, "Wire
    compression"), which stays the user's call: pass only the formats
    whose accuracy budget fits the model.
    """
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import basics
    from horovod_tpu.parallel import mesh as mesh_lib
    from horovod_tpu.utils.benchmarks import WindowTime, slope_window, sync

    from horovod_tpu.ops import compression as compression_lib

    if candidates is None:
        candidates = [1 << 20, 4 << 20, 16 << 20, 64 << 20]
    joint = wire_candidates is not None
    if joint:
        for w in wire_candidates:
            compression_lib.by_name(w)  # fail fast on a typo'd wire name
        keys = [(thr, w) for thr in candidates for w in wire_candidates]
    else:
        keys = list(candidates)
    try:
        mesh = mesh_lib.get_mesh()
    except RuntimeError:
        mesh = None
    axes_t = collective._resolve_axes(axes) if mesh is not None else axes

    # world size over the reduction axes: mesh participants on the
    # compiled path; on the eager fallback the participant set is the
    # native core's world when it is up (hvdrun multi-process without
    # jax.distributed — jax.process_count() is 1 per process there),
    # else the jax process count
    if mesh is not None:
        shape = dict(zip(mesh.axis_names, mesh.devices.shape))
        world = int(np.prod([shape[a] for a in axes_t]))
    else:
        from horovod_tpu import _core as _core_probe
        world = (_core_probe.size() if _core_probe.is_initialized()
                 else jax.process_count())
    if world <= 1:
        return None, AutotuneTimings(abstain_reason=(
            f"world size 1 over axes {axes_t!r}: the fused collectives "
            "are local no-ops, so threshold timings carry no signal"))

    if joint and mesh is None:
        # the eager fallback times trials WITHOUT shard_map; chunked
        # quantizers need the compiled mesh path (fused_allreduce would
        # raise mid-trial and kill the whole search) — drop them from
        # the grid loudly and rank what can be measured
        dropped = sorted({
            w for w in wire_candidates
            if getattr(compression_lib.by_name(w), "chunked", False)})
        if dropped:
            import warnings
            warnings.warn(
                f"dropping chunked wire candidates {dropped} from the "
                "autotune grid: no compiled mesh is available (the eager "
                "fallback cannot run the quantized exchange). Initialize "
                "the mesh (hvd.init()) to rank fp8/int8.")
            keys = [k for k in keys if k[1] not in dropped]
        if not keys:
            return None, AutotuneTimings(abstain_reason=(
                "every wire candidate is a chunked quantizer and no "
                "compiled mesh is available to time them"))

    timings = AutotuneTimings()
    for key in keys:
        thr, wire_name = key if joint else (key, None)

        def f(t, salt, _thr=thr, _wire=wire_name):
            # salt-shift every leaf: distinct inputs per trial call, and
            # the reduced output (fed back as the next input) keeps
            # drifting, so no two calls are memoizable as pure replays.
            def shift(x):
                x = jnp.asarray(x)
                if jnp.issubdtype(x.dtype, jnp.floating):
                    return x + (salt * jnp.finfo(jnp.float32).eps).astype(
                        x.dtype)
                return x
            t = jax.tree_util.tree_map(shift, t)
            return fused_allreduce(t, op=op, axes=axes_t,
                                   threshold_bytes=_thr,
                                   compression=_wire)
        if mesh is not None:
            spec = jax.tree_util.tree_map(lambda _: P(), tree)
            f = jax.shard_map(f, mesh=mesh, in_specs=(spec, P()),
                              out_specs=spec, check_vma=False)
        jf = jax.jit(f)
        salt0 = jnp.zeros((), jnp.float32)
        sync(jf(tree, salt0))  # compile + true completion, outside timing

        def step_once(st):
            t, salt = st
            out = jf(t, salt)
            return (out, salt + 1.0), out

        st = (tree, salt0 + 1.0)
        dt, st = slope_window(step_once, st, trials)
        # Inverted slope window: the trial produced a full-window UPPER
        # BOUND (fixed dispatch costs included), not a measurement —
        # ranking candidates on it compares noise. The BENCH_r05 noise
        # source was exactly this tail: doubling crept up too slowly to
        # clear the fixed-cost floor within its cap, so bounds leaked
        # into the ranking. Escalate HARD instead — x4 per retry,
        # bounded at 16x — and count every escalation so the BENCH json
        # can tell a measured threshold from a guessed bound.
        iters = trials
        if dt.upper_bound:
            timings.retried += 1
            while dt.upper_bound and iters < trials * 16:
                iters *= 4
                timings.slope_window_escalations += 1
                dt, st = slope_window(step_once, st, iters)
        # normalize retried trials back to seconds-per-`trials`-iters so
        # candidates stay comparable under argmin
        timings[key] = WindowTime(float(dt) * trials / iters,
                                  upper_bound=dt.upper_bound,
                                  asymmetric=dt.asymmetric)

    # Multi-process: every rank must install the SAME winner, or ranks
    # would plan different bucket structures and emit mismatched
    # collectives. Sum the timings across ranks, then argmin — a
    # deterministic, globally identical choice. The upper-bound flags
    # ride along (max across ranks) so the abstain decision below is
    # identical everywhere too.
    from horovod_tpu import _core
    if _core.is_initialized() and _core.size() > 1:
        vals = np.asarray(
            [timings[c] for c in keys]
            + [float(getattr(timings[c], "upper_bound", False))
               for c in keys], np.float64)
        n = _AUTOTUNE_CALLS.setdefault("n", 0)
        _AUTOTUNE_CALLS["n"] = n + 1
        summed = _core.allreduce(vals, f"autotune.fusion.{n}", op="sum")
        timings = AutotuneTimings(
            {c: WindowTime(float(s), upper_bound=bool(b > 0))
             for c, s, b in zip(keys, summed, summed[len(keys):])},
            retried=timings.retried,
            slope_window_escalations=timings.slope_window_escalations)

    def _fmt_key(c):
        if joint:
            return f"{c[0] >> 20}MB/{c[1]}"
        return f"{c >> 20}MB"

    best = min(timings, key=timings.get)
    best_val = float(timings[best])
    # Abstain on unresolved bounds near the argmin: an upper BOUND only
    # says "the true time is <= this", so any bounded candidate within
    # `tolerance` of (or below) the best value could secretly be the
    # winner — publishing an argmin over it would install noise.
    unresolved = sorted(
        c for c in keys
        if getattr(timings[c], "upper_bound", False)
        and float(timings[c]) <= best_val * (1.0 + tolerance))
    if unresolved:
        timings.abstain_reason = (
            f"candidate(s) {[_fmt_key(c) for c in unresolved]} are still "
            f"inverted-window upper bounds within {tolerance:.0%} of the "
            "best measured time after retries; the argmin would rank "
            "noise — keeping the current default")
        return None, timings
    if apply and basics._state.config is not None:
        if joint:
            basics._state.config.fusion_threshold = best[0]
            basics._state.config.wire_dtype = (
                None if best[1] in (None, "none") else best[1])
        else:
            basics._state.config.fusion_threshold = best
    return best, timings


_AUTOTUNE_CALLS = {}
