"""Gradient compression for the collective wire format.

Mirrors ``horovod/torch/compression.py`` / ``horovod/tensorflow/compression.py``
(74 LoC each) — a ``Compression`` namespace whose members expose
``compress(tensor) -> (tensor, ctx)`` / ``decompress(tensor, ctx)`` — and
goes beyond the reference with TPU-native sub-16-bit wire formats.

Two families live here, distinguished by whether the wire format survives
an in-flight reduction:

* **Cast compressors** (``bf16``/``fp16``/``float16``): a plain dtype cast.
  Sums of cast values are meaningful, so the collective itself can run at
  the wire dtype (``psum``/``psum_scatter`` in bf16) — the reference
  ``FP16Compressor`` model. TPU-first default is **bfloat16** (MXU/ICI
  native, fp32 exponent range, no loss scaling needed).

* **Chunked quantizers** (``fp8_e4m3``/``fp8_e5m2``/``int8``): each chunk
  of the flat bucket is scaled by its own fp32 scale (absmax mapped onto
  the wire format's representable range) before narrowing. Quantized
  values under DIFFERENT scales cannot be summed on the wire, so these
  carry ``chunked = True`` and the fusion pipeline routes them through
  exchange-then-locally-reduce collectives (all-to-all for the
  reduce-scatter half) instead of an in-wire ``psum`` —
  ``ops/fusion.py``. The per-bucket error-feedback residual that keeps
  the training trajectory glued to the exact path is computed from
  :meth:`ChunkedQuantizer.roundtrip` and threaded through the train
  state by ``training.make_train_step`` (docs/PERFORMANCE.md, "Wire
  compression").

Non-float leaves (integer/bool gradients — rare, but e.g. embedding hit
counters ride gradient pytrees) are never narrowed: they pass through at
their own dtype with ``ctx=None`` and must round-trip **bit-exactly**.
Telemetry accounts them at their true wire width — the logical-vs-wire
byte counters in ``ops/collective.py`` only credit compression for bytes
that were actually narrowed.
"""

import jax.numpy as jnp
import numpy as np


class NoneCompressor:
    """Pass-through (reference ``NoneCompressor``)."""

    name = "none"
    chunked = False

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        del ctx
        return tensor


class _CastCompressor:
    """Cast floating tensors to a narrow wire dtype for the collective, cast
    back after (reference ``FP16Compressor``). The wire format is reducible:
    collectives may sum at the wire dtype."""

    chunked = False

    def __init__(self, wire_dtype, name=None):
        self.wire_dtype = wire_dtype
        self.name = name or str(np.dtype(wire_dtype))

    def compress(self, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != self.wire_dtype:
            return tensor.astype(self.wire_dtype), dtype
        return tensor, None

    def decompress(self, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor

    # -- bucket-level interface (shared with ChunkedQuantizer) -------------
    # The fusion pipeline talks to every wire format through
    # compress_flat/decompress_flat so the cast and quantize families are
    # interchangeable per bucket; for a cast wire the "scales" slot is None.

    def compress_flat(self, flat):
        """``flat [..., n] -> (wire [..., n], scales=None)``."""
        if not jnp.issubdtype(flat.dtype, jnp.floating):
            return flat, None
        return flat.astype(self.wire_dtype), None

    def decompress_flat(self, wire, scales, dtype, n=None):
        del scales
        out = wire.astype(dtype)
        if n is not None and out.shape[-1] != n:
            out = out[..., :n]
        return out

    def roundtrip(self, flat):
        """``(wire, scales, dequantized)`` — the dequantized view feeds the
        error-feedback residual (``flat - dequantized``)."""
        wire, _ = self.compress_flat(flat)
        return wire, None, wire.astype(flat.dtype)

    def wire_bytes(self, n_elements, logical_dtype):
        """Bytes this wire format puts on the interconnect for
        ``n_elements`` of ``logical_dtype`` (non-float leaves ride
        uncompressed)."""
        if not jnp.issubdtype(jnp.dtype(logical_dtype), jnp.floating):
            return int(n_elements) * np.dtype(logical_dtype).itemsize
        return int(n_elements) * np.dtype(self.wire_dtype).itemsize


# Default elements per fp32 scale. 256 keeps the scale overhead at
# 4/256 = 1.6% of the logical bytes while bounding every element's
# distance from its chunk absmax (the quantization step is
# absmax/range_max PER CHUNK, not per bucket — a single huge gradient
# spike only coarsens its own 256 neighbours).
DEFAULT_CHUNK = 256


class ChunkedQuantizer:
    """Narrow wire dtype + one fp32 scale per ``chunk`` elements.

    ``compress_flat(flat [..., n]) -> (wire [..., n_pad], scales [..., c])``
    chunks along the LAST axis (the flat-bucket axis in the fusion
    pipeline; leading axes — the ``[world, shard]`` row layout of the
    reduce-scatter exchange — are preserved, so chunks never straddle a
    shard boundary and each destination rank can decode its rows from the
    scales it received). ``n_pad`` rounds ``n`` up to a chunk multiple;
    ``decompress_flat(..., n=n)`` slices the pad back off.

    The wire is NOT reducible (``chunked = True``): per-chunk scales
    differ across ranks, so the exchange must decompress before summing.
    """

    chunked = True

    def __init__(self, wire_dtype, range_max, name, chunk=DEFAULT_CHUNK,
                 integer=False):
        self.wire_dtype = wire_dtype
        self.range_max = float(range_max)
        self.name = name
        self.chunk = int(chunk)
        self.integer = integer

    def __repr__(self):
        return f"ChunkedQuantizer({self.name}, chunk={self.chunk})"

    def _padded(self, n):
        return n + (-n) % self.chunk

    def for_length(self, n):
        """Quantizer with the chunk clamped to a payload of ``n`` elements:
        a reduce-scatter shard smaller than the configured chunk would
        otherwise pay chunk-rounding padding on every row of the exchange
        (a 1-element shard shipping 256 wire bytes). Both ends of a
        collective derive the clamped quantizer from the same static shard
        size, so encode and decode always agree."""
        if n >= self.chunk:
            return self
        return ChunkedQuantizer(self.wire_dtype, self.range_max, self.name,
                                chunk=max(1, int(n)), integer=self.integer)

    def compress_flat(self, flat):
        wire, scales, _ = self._quantize(flat, want_dequant=False)
        return wire, scales

    def roundtrip(self, flat):
        """``(wire, scales, dequantized)`` in one pass — the error-feedback
        residual is ``flat - dequantized`` and reusing the quantize
        intermediates keeps it one multiply instead of a second decode."""
        return self._quantize(flat, want_dequant=True)

    def _quantize(self, flat, want_dequant):
        if not jnp.issubdtype(flat.dtype, jnp.floating):
            # non-float leaves are never narrowed — bit-exact passthrough
            return flat, None, flat
        n = flat.shape[-1]
        pad = self._padded(n) - n
        x = flat.astype(jnp.float32)
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros(x.shape[:-1] + (pad,), jnp.float32)], axis=-1)
        chunks = x.reshape(x.shape[:-1] + (-1, self.chunk))
        absmax = jnp.max(jnp.abs(chunks), axis=-1)
        # a zero chunk keeps scale 1 so 0/scale stays 0 (no NaN lanes)
        scales = jnp.where(absmax > 0.0, absmax / self.range_max, 1.0)
        scaled = chunks / scales[..., None]
        if self.integer:
            q = jnp.clip(jnp.round(scaled), -self.range_max, self.range_max)
            wire = q.astype(self.wire_dtype)
        else:
            wire = scaled.astype(self.wire_dtype)
        wire = wire.reshape(x.shape)
        deq = None
        if want_dequant:
            deq = (wire.astype(jnp.float32)
                   .reshape(chunks.shape) * scales[..., None])
            deq = deq.reshape(x.shape)[..., :n].astype(flat.dtype)
        return wire, scales, deq

    def decompress_flat(self, wire, scales, dtype, n=None):
        """Inverse of :meth:`compress_flat`: ``wire [..., n_pad]`` +
        ``scales [..., c]`` back to ``[..., n]`` at ``dtype``."""
        if scales is None:  # non-float passthrough
            return wire if n is None else wire[..., :n]
        chunks = wire.astype(jnp.float32).reshape(
            wire.shape[:-1] + (-1, self.chunk))
        out = (chunks * scales[..., None]).reshape(wire.shape)
        if n is not None:
            out = out[..., :n]
        return out.astype(dtype)

    def wire_bytes(self, n_elements, logical_dtype):
        """Interconnect bytes for ``n_elements`` of ``logical_dtype``:
        padded wire payload + the fp32 scales riding with it (non-float
        leaves pass through at full width)."""
        if not jnp.issubdtype(jnp.dtype(logical_dtype), jnp.floating):
            return int(n_elements) * np.dtype(logical_dtype).itemsize
        n_pad = self._padded(int(n_elements))
        n_scales = n_pad // self.chunk
        return (n_pad * np.dtype(self.wire_dtype).itemsize
                + n_scales * 4)

    # -- reference-shaped eager interface ---------------------------------
    # compress/decompress(tensor, ctx) keep the Compression namespace
    # uniform for user code that round-trips a single tensor outside the
    # fusion pipeline. ctx carries (scales, dtype, n, shape).

    def compress(self, tensor):
        if not jnp.issubdtype(tensor.dtype, jnp.floating):
            return tensor, None
        flat = tensor.reshape(-1)
        wire, scales = self.compress_flat(flat)
        return wire, (scales, tensor.dtype, flat.shape[-1],
                      tensor.shape)

    def decompress(self, tensor, ctx):
        if ctx is None:
            return tensor
        scales, dtype, n, shape = ctx
        return self.decompress_flat(tensor, scales, dtype, n).reshape(shape)


# fp8 representable maxima (finite): e4m3fn tops out at 448, e5m2 at 57344.
# Scaling each chunk's absmax onto the format maximum spends the full
# mantissa on every chunk regardless of the gradient's absolute magnitude.
_E4M3_MAX = 448.0
_E5M2_MAX = 57344.0


class Compression:
    """Namespace matching the reference API — ``Compression.none``,
    ``Compression.fp16`` (bfloat16 wire on TPU), ``Compression.bf16``,
    ``Compression.float16`` (true IEEE fp16 wire) — plus the sub-byte
    chunked-scale wire formats: ``fp8_e4m3`` (3 mantissa bits — the
    default fp8 pick), ``fp8_e5m2`` (wider exponent, coarser mantissa),
    ``int8`` (symmetric per-chunk scale, round-to-nearest). ``fp8`` is
    an alias for ``fp8_e4m3``."""

    none = NoneCompressor()
    bf16 = _CastCompressor(jnp.bfloat16)
    fp16 = bf16  # TPU-native 16-bit wire format
    float16 = _CastCompressor(jnp.float16)
    fp8_e4m3 = ChunkedQuantizer(jnp.float8_e4m3fn, _E4M3_MAX, "fp8_e4m3")
    fp8_e5m2 = ChunkedQuantizer(jnp.float8_e5m2, _E5M2_MAX, "fp8_e5m2")
    fp8 = fp8_e4m3
    int8 = ChunkedQuantizer(jnp.int8, 127.0, "int8", integer=True)


_BY_NAME = {
    "none": None,
    "bf16": Compression.bf16,
    "fp16": Compression.bf16,
    "float16": Compression.float16,
    "fp8": Compression.fp8_e4m3,
    "fp8_e4m3": Compression.fp8_e4m3,
    "fp8_e5m2": Compression.fp8_e5m2,
    "int8": Compression.int8,
}


def by_name(name):
    """Resolve a wire-dtype name (config/autotune/bench surface) to a
    compressor; ``"none"``/``None`` mean uncompressed."""
    if name is None:
        return None
    try:
        return _BY_NAME[str(name).lower()]
    except KeyError:
        raise ValueError(
            f"unknown wire dtype {name!r}; pick one of "
            f"{sorted(_BY_NAME)}") from None
