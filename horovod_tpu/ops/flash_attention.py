"""Fused flash attention as a Pallas TPU kernel.

The attention hot path of the transformer family (models/transformer.py)
as a VMEM-resident kernel: the grid is (batch*head, q-block, kv-block)
with the kv dimension innermost, so K/V stream through VMEM one
(block_k, d) tile at a time while fp32 scratch accumulators carry the
online-softmax (flash) recurrence across kv steps — the S x S score
matrix never exists and VMEM usage is bounded by the block sizes, not
the sequence length (reference role: the fused attention kernels every
CUDA framework hand-writes; see /opt/skills/guides/pallas_guide.md).

Sequence-parallel composition: ``q_offset``/``kv_offset`` give the
absolute position of the first query/key token. They ride a
scalar-prefetch argument (SMEM), so traced values — e.g. derived from
``lax.axis_index`` inside a shard_map — work; a shard holding a rotated
K/V block passes that block's global offset and the causal mask stays
exact. A query row with no visible keys outputs zeros (not a spurious
mean of V).

Gradients: custom VJP with **fused backward kernels** — a dQ pass
(kv-blocks streamed) and a dK/dV pass (q-blocks streamed), each
recomputing P blockwise from (q, k, lse) saved by the forward — so the
backward, like the forward, never materializes S x S and stays
O(S * block) in memory (the flash-attention rematerialization policy).
Kernel matmuls run at the MXU's default precision with fp32
accumulation, matching XLA's own default on TPU. Falls back
transparently (``attention`` helper) to the plain-XLA path when shapes
don't tile; the kernels run anywhere under ``interpret=True``, which is
how the CPU test suite exercises them.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # Mosaic TPU backend; absent on some CPU-only installs
    from jax.experimental.pallas import tpu as pltpu
# hvd-lint: disable=HVD-EXCEPT -- import probe: Mosaic backend absent on CPU-only installs
except Exception:  # pragma: no cover
    pltpu = None

# Measured on v5e (bf16 operands, fwd+bwd, b8 h12 s2048 d64): 512x512
# blocks run 4x faster than 128x128 — bigger tiles amortize grid/VPU
# overhead and keep the MXU fed; beyond 512 the curve is flat to slightly
# worse. Blocks clamp to the sequence, so short inputs still tile.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
NEG_INF = -1e30


def _kernel(off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
            *, block_q, block_k, causal, sm_scale, lse_ref=None):
    """One (bh, q-block, kv-block) grid step. Scratch (m, l, acc) carries
    the online-softmax state across the innermost kv dimension."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    nkv = pl.num_programs(2)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_pos = (q_off + i * block_q
             + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0))
    kv_start = kv_off + j * block_k

    def _update():
        # matmuls run on NATIVE-dtype operands (bf16 inputs hit the
        # MXU's bf16 multipliers — fp32 operands would run at a
        # fraction of peak) with fp32 accumulation; all softmax math
        # stays fp32. sm_scale is applied to the fp32 scores, not the
        # narrow inputs.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T,
                    preferred_element_type=jnp.float32) * sm_scale
        if causal:
            kv_pos = (kv_start +
                      jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1))
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        m = m_ref[:]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        # rows with nothing visible yet keep p = 0, so a fully-masked
        # query outputs zeros instead of a spurious mean of V
        p = jnp.where(m_new <= NEG_INF / 2, 0.0, jnp.exp(s - m_new))
        scale = jnp.where(m <= NEG_INF / 2, 0.0, jnp.exp(m - m_new))
        l_ref[:] = l_ref[:] * scale + jnp.sum(p, axis=-1, keepdims=True)
        # p cast to the value dtype for the MXU (the standard flash
        # choice); accumulation stays fp32 in scratch
        acc_ref[:] = acc_ref[:] * scale + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # skip kv blocks the causal mask kills entirely (scalar math
        # only — extracting from a vector is a Mosaic dynamic_slice)
        q_last = q_off + i * block_q + (block_q - 1)
        pl.when(q_last >= kv_start)(_update)
    else:
        _update()

    @pl.when(j == nkv - 1)
    def _finalize():
        l = l_ref[:]
        o_ref[0] = (acc_ref[:] / jnp.where(l == 0.0, 1.0, l)).astype(
            o_ref.dtype)
        if lse_ref is not None:
            # log-sum-exp per query row; NEG_INF marks "nothing visible"
            # so cross-block combination gives this block zero weight
            lse_ref[0] = jnp.where(
                l == 0.0, NEG_INF,
                m_ref[:] + jnp.log(jnp.where(l == 0.0, 1.0, l)))


def _kernel_lse(off_ref, q_ref, k_ref, v_ref, o_ref, lse_out_ref, m_ref,
                l_ref, acc_ref, **kw):
    _kernel(off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref,
            lse_ref=lse_out_ref, **kw)


def _flash_fwd_impl(q, k, v, offsets, causal, sm_scale, block_q, block_k,
                    interpret, with_lse=False):
    """q: [BH, Sq, D]; k/v: [BH, Skv, D]; offsets: int32[2] -> [BH, Sq, D]
    (plus fp32 [BH, Sq, 1] log-sum-exp rows when ``with_lse`` — the
    trailing singleton satisfies Mosaic's last-two-dims tiling rule)."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    kw = dict(block_q=block_q, block_k=block_k, causal=causal,
              sm_scale=sm_scale)
    kern = functools.partial(_kernel_lse if with_lse else _kernel, **kw)
    out_specs = pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0))
    out_shape = jax.ShapeDtypeStruct((bh, sq, d), q.dtype)
    if with_lse:
        # lse rides as [BH, Sq, 1]: a (1, bq, 1) block satisfies the
        # Mosaic last-two-dims tiling rule where a 2-D (1, bq) cannot
        out_specs = (out_specs,
                     pl.BlockSpec((1, block_q, 1),
                                  lambda b, i, j, *_: (b, i, 0)))
        out_shape = (out_shape,
                     jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, sq // block_q, skv // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j, *_: (b, j, 0)),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # m
            pltpu.VMEM((block_q, 1), jnp.float32),   # l
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
        ],
    )
    return pl.pallas_call(
        kern,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(offsets, q, k, v)


def _reference_attention(q, k, v, offsets, causal, sm_scale):
    """Plain-XLA fp32 attention on [BH, S, D] — the backward-pass
    recompute target and the correctness oracle in tests. Matches the
    kernel's fully-masked-row-outputs-zero convention."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    if causal:
        qp = offsets[0] + jnp.arange(q.shape[1])[:, None]
        kp = offsets[1] + jnp.arange(k.shape[1])[None, :]
        mask = qp >= kp
        s = jnp.where(mask, s, NEG_INF)
        any_visible = jnp.any(mask, axis=-1)[None, :, None]
    else:
        any_visible = True
    p = jax.nn.softmax(s, axis=-1)
    p = jnp.where(any_visible, p, 0.0)
    return jnp.einsum("bqk,bkd->bqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _dq_kernel(off_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
               dq_ref, dq_acc, *, block_q, block_k, causal, sm_scale):
    """Backward dQ pass: grid (bh, q-block, kv-block), kv innermost.
    Recomputes P from (q, k, lse) blockwise — flash backward proper."""
    i = pl.program_id(1)
    j = pl.program_id(2)
    nkv = pl.num_programs(2)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(j == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    def _update():
        # native-dtype matmul operands + fp32 accumulation (see _kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = (q_off + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0))
            kv_pos = (kv_off + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1))
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        # p = exp(s - lse); rows with nothing visible have lse=NEG_INF
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_acc[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        q_last = q_off + i * block_q + (block_q - 1)
        pl.when(q_last >= kv_off + j * block_k)(_update)
    else:
        _update()

    @pl.when(j == nkv - 1)
    def _finalize():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(off_ref, q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                causal, sm_scale):
    """Backward dK/dV pass: grid (bh, kv-block, q-block), q innermost."""
    j = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)
    q_off = off_ref[0]
    kv_off = off_ref[1]

    @pl.when(i == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _update():
        # native-dtype matmul operands + fp32 accumulation (see _kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = (q_off + i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, 1), 0))
            kv_pos = (kv_off + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1))
            s = jnp.where(q_pos >= kv_pos, s, NEG_INF)
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc[:] += jnp.dot(p.astype(g.dtype).T, g,
                             preferred_element_type=jnp.float32)
        dp = jnp.dot(g, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_acc[:] += jnp.dot(ds.T, q, preferred_element_type=jnp.float32)

    if causal:
        q_last = q_off + i * block_q + (block_q - 1)
        pl.when(q_last >= kv_off + j * block_k)(_update)
    else:
        _update()

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, g, out, lse, offsets, causal, sm_scale,
                    block_q, block_k, interpret):
    """Fused flash backward: dq pass then dk/dv pass, each streaming the
    other operand; memory is O(S * block), never O(S^2)."""
    # delta_i = sum_d dO * O — the softmax-jacobian row correction
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [BH, Sq, 1]
    return _flash_bwd_core(q, k, v, g, lse, delta, offsets, causal,
                           sm_scale, block_q, block_k, interpret)


def _flash_bwd_core(q, k, v, g, lse, delta, offsets, causal, sm_scale,
                    block_q, block_k, interpret, out_dtype=None):
    """The two backward kernel launches, with (lse, delta) supplied by
    the caller. Ring attention calls this per rotated K/V block with the
    globally-merged lse and the once-computed global delta — the
    per-block partials then sum to the exact global-softmax gradient
    (softmax over the union of blocks factorizes as p = exp(s - LSE)).
    ``out_dtype`` lets accumulating callers request fp32 partials."""
    bh, sq, d = q.shape
    skv = k.shape[1]
    # grads mirror their primal dtypes (custom_vjp aval contract) unless
    # the caller wants uniform fp32 partials for accumulation
    dq_dtype = out_dtype or q.dtype
    dk_dtype = out_dtype or k.dtype
    dv_dtype = out_dtype or v.dtype
    kw = dict(block_q=block_q, block_k=block_k, causal=causal,
              sm_scale=sm_scale)
    qspec = lambda b, i, j, *_: (b, i, 0)      # noqa: E731
    kspec = lambda b, i, j, *_: (b, j, 0)      # noqa: E731
    rowspec = lambda b, i, j, *_: (b, i, 0)    # noqa: E731
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **kw),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, sq // block_q, skv // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), qspec),
                pl.BlockSpec((1, block_k, d), kspec),
                pl.BlockSpec((1, block_k, d), kspec),
                pl.BlockSpec((1, block_q, d), qspec),
                pl.BlockSpec((1, block_q, 1), rowspec),
                pl.BlockSpec((1, block_q, 1), rowspec),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), qspec),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), dq_dtype),
        interpret=interpret,
    )(offsets, q, k, v, g, lse, delta)

    # second pass: kv-block outer, q-block inner
    qspec2 = lambda b, j, i, *_: (b, i, 0)     # noqa: E731
    kspec2 = lambda b, j, i, *_: (b, j, 0)     # noqa: E731
    rowspec2 = lambda b, j, i, *_: (b, i, 0)   # noqa: E731
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, **kw),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, skv // block_k, sq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), qspec2),
                pl.BlockSpec((1, block_k, d), kspec2),
                pl.BlockSpec((1, block_k, d), kspec2),
                pl.BlockSpec((1, block_q, d), qspec2),
                pl.BlockSpec((1, block_q, 1), rowspec2),
                pl.BlockSpec((1, block_q, 1), rowspec2),
            ],
            out_specs=(pl.BlockSpec((1, block_k, d), kspec2),
                       pl.BlockSpec((1, block_k, d), kspec2)),
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=(jax.ShapeDtypeStruct((bh, skv, d), dk_dtype),
                   jax.ShapeDtypeStruct((bh, skv, d), dv_dtype)),
        interpret=interpret,
    )(offsets, q, k, v, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, offsets, causal, sm_scale, block_q, block_k,
           interpret):
    return _flash_fwd_impl(q, k, v, offsets, causal, sm_scale, block_q,
                           block_k, interpret)


def _flash_fwd(q, k, v, offsets, causal, sm_scale, block_q, block_k,
               interpret):
    out, lse = _flash_fwd_impl(q, k, v, offsets, causal, sm_scale,
                               block_q, block_k, interpret, with_lse=True)
    return out, (q, k, v, offsets, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, interpret, res, g):
    q, k, v, offsets, out, lse = res
    dq, dk, dv = _flash_bwd_impl(q, k, v, g, out, lse, offsets, causal,
                                 sm_scale, block_q, block_k, interpret)
    return dq, dk, dv, None


_flash.defvjp(_flash_fwd, _flash_bwd)


def _fit_block(n, preferred):
    """Largest block <= preferred that divides ``n`` and respects the
    fp32 sublane tile (8), halving down from the preferred size; 0 when
    nothing fits. Keeps big-block performance for the common pow2
    sequences without dropping support for e.g. seq 1280 (divides by
    256) or 1152 (divides by 128)."""
    b = min(preferred, n)
    while b >= 8:
        if n % b == 0 and b % 8 == 0:
            return b
        b //= 2
    return 0


MIN_BLOCK = 128  # MXU tile width: narrower blocks starve the systolic array


def _block_ok(n, preferred):
    """A fitted block is worth running only when it either spans the
    whole (short) sequence or meets the MXU floor: a long sequence whose
    only fitting block is tiny (e.g. 1048 -> 8) would issue 8-wide MXU
    ops all the way down — slower than the dense XLA path it replaces
    (ADVICE round 5)."""
    b = _fit_block(n, preferred)
    return b > 0 and (b == n or b >= MIN_BLOCK)


def kernel_supported(sq, skv, d, block_q=DEFAULT_BLOCK_Q,
                     block_k=DEFAULT_BLOCK_K):
    """True when these shapes tile onto the kernel (callers use this to
    fall back to the plain-XLA path)."""
    if pltpu is None:
        return False
    # incremental-decode shapes (q_len == 1 — one new token per sequence
    # against a long cached K/V, the serve/engine.py hot loop) can never
    # tile onto an MXU-floor block: route them to the dense path
    # EXPLICITLY rather than relying on the block fit to bottom out —
    # the contract a decode caller depends on deserves its own gate
    # (and its own test), not an emergent property of _fit_block
    if sq == 1 or skv == 1:
        return False
    # blocks must respect the fp32 sublane tile (8) or Mosaic can
    # reject the lowering — the fallback contract depends on this gate —
    # and clear the MXU floor, or the dense fallback is faster
    return (d % 8 == 0 and _block_ok(sq, block_q)
            and _block_ok(skv, block_k))


def _prep(q, k, v, sm_scale, block_q, block_k, interpret):
    """Shared prologue: defaulting, tiling validation, and the
    [B,S,H,D] -> [BH,S,D] relayout."""
    if pltpu is None:
        raise RuntimeError("pallas TPU backend unavailable; use "
                           "ops.flash_attention.attention (auto-fallback)")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    b, sq, h, d = q.shape
    skv = k.shape[1]
    sm_scale = sm_scale if sm_scale is not None else 1.0 / (float(d) ** 0.5)
    bq, bk = _fit_block(sq, block_q), _fit_block(skv, block_k)
    if bq == 0 or bk == 0 or d % 8 != 0:
        raise ValueError(
            f"flash_attention needs a block (divisible by 8) that divides "
            f"S, and d % 8 == 0 (sq={sq}, skv={skv}, d={d}); use "
            f"ops.flash_attention.attention for automatic fallback")

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    return to_bh, (b, sq, h, d), sm_scale, bq, bk, interpret


def flash_attention(q, k, v, *, causal=True, sm_scale=None, q_offset=0,
                    kv_offset=0, block_q=DEFAULT_BLOCK_Q,
                    block_k=DEFAULT_BLOCK_K, interpret=None):
    """Fused attention on [B, S, H, D] tensors (the transformer layout).

    ``q_offset``/``kv_offset`` are the absolute positions of the first
    query/key token; ints or traced int32 scalars both work (they ride a
    scalar-prefetch argument), so a sequence-parallel shard can pass
    ``lax.axis_index(...) * s_local`` for a rotated K/V block."""
    to_bh, (b, sq, h, d), sm_scale, bq, bk, interpret = _prep(
        q, k, v, sm_scale, block_q, block_k, interpret)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(kv_offset, jnp.int32)])
    out = _flash(to_bh(q), to_bh(k), to_bh(v), offsets, causal, sm_scale,
                 bq, bk, interpret)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def flash_attention_with_lse(q, k, v, *, causal=True, sm_scale=None,
                             q_offset=0, kv_offset=0,
                             block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K, interpret=None):
    """Forward-only kernel call returning ``(out, lse)`` with
    ``lse[b, s, h]`` the log-sum-exp of each query row (NEG_INF when the
    row sees no keys). This is the blockwise-composition primitive: ring
    attention runs it per rotated K/V block and merges results by lse
    weighting (parallel/ring.py). Differentiation happens at the ring
    level, so this call is deliberately VJP-free."""
    to_bh, (b, sq, h, d), sm_scale, bq, bk, interpret = _prep(
        q, k, v, sm_scale, block_q, block_k, interpret)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(kv_offset, jnp.int32)])
    out, lse = _flash_fwd_impl(to_bh(q), to_bh(k), to_bh(v), offsets,
                               causal, sm_scale, bq, bk, interpret,
                               with_lse=True)
    out = out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    lse = lse.reshape(b, h, sq).transpose(0, 2, 1)  # [BH,Sq,1] -> [B,S,H]
    return out, lse


def flash_attention_bwd_block(q, k, v, g, lse, delta, *, causal=True,
                              sm_scale=None, q_offset=0, kv_offset=0,
                              block_q=DEFAULT_BLOCK_Q,
                              block_k=DEFAULT_BLOCK_K, interpret=None):
    """Per-block fused backward for blockwise/ring composition: given
    this rank's queries ``q`` [B,Sq,H,D], one rotated K/V block
    [B,Skv,H,D], the upstream ``g`` = dO, the **globally merged**
    ``lse`` [B,Sq,H] (from ``flash_attention_with_lse`` + lse merging)
    and ``delta`` [B,Sq,H] = sum_d(dO * O) over the final output, runs
    the fused dQ and dK/dV kernels and returns fp32 partials
    ``(dq, dk, dv)`` for exactly this block's contribution. Summing the
    partials over all blocks (rotating dk/dv with their K/V blocks
    around the ring) reproduces the exact global-softmax gradient,
    because p = exp(s - LSE) factorizes per block once LSE is global —
    the ring backward never materializes an S x S score matrix
    (parallel/ring.py ``_ring_attention_flash``)."""
    to_bh, (b, sq, h, d), sm_scale, bq, bk, interpret = _prep(
        q, k, v, sm_scale, block_q, block_k, interpret)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(kv_offset, jnp.int32)])

    def rows_bh(x):  # [B,Sq,H] -> [BH,Sq,1]
        return x.transpose(0, 2, 1).reshape(b * h, sq, 1)

    dq, dk, dv = _flash_bwd_core(
        to_bh(q), to_bh(k), to_bh(v), to_bh(g), rows_bh(lse),
        rows_bh(delta), offsets, causal, sm_scale, bq, bk, interpret,
        out_dtype=jnp.float32)

    def from_bh(x, s):
        return x.reshape(b, h, s, d).transpose(0, 2, 1, 3)

    skv = k.shape[1]
    return from_bh(dq, sq), from_bh(dk, skv), from_bh(dv, skv)


def attention(q, k, v, *, causal=True, q_offset=0, kv_offset=0):
    """flash_attention with automatic fallback to the plain-XLA path
    when shapes don't tile onto the kernel blocks."""
    b, sq, h, d = q.shape
    skv = k.shape[1]
    if kernel_supported(sq, skv, d):
        return flash_attention(q, k, v, causal=causal, q_offset=q_offset,
                               kv_offset=kv_offset)
    offsets = jnp.asarray([q_offset, kv_offset], jnp.int32)

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, x.shape[1], d)

    out = _reference_attention(to_bh(q), to_bh(k), to_bh(v), offsets,
                               causal, 1.0 / (float(d) ** 0.5))
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
