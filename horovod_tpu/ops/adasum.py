"""Adasum: scale-insensitive gradient combination (Microsoft).

Reference: header-only templated implementation with AVX fp16 intrinsics and
an MPI recursive vector-halving distance-doubling schedule
(``horovod/common/ops/adasum/adasum.h:186-330`` ``FusedAllreduce``,
pairwise combine at ``adasum.h:331+``; MPI instantiation
``adasum_mpi.cc``; hierarchical GPU variant ``adasum_cuda_operations.cc``).

The pairwise operator for gradients a, b is::

    combined = a * (1 - dot(a,b) / (2*||a||^2))
             + b * (1 - dot(a,b) / (2*||b||^2))

applied recursively over a binary tree of ranks (power-of-2 world size,
same constraint as the reference). TPU-native realization: each tree level
is a full-vector ``ppermute`` exchange with the XOR partner followed by the
combine, entirely inside the compiled step — the dot products and norms are
accumulated in **float32** regardless of wire dtype (the reference needs
hand-written AVX fp16 dot kernels for this; on TPU we just ask XLA for f32
accumulation).

The tree order is identical to the reference's recursive-halving schedule,
so a NumPy reference model (see ``tests/test_adasum.py``) reproduces results
bit-for-bit in f32.
"""

import jax.numpy as jnp
import numpy as np
from jax import lax


def adasum_combine(a, b, eps=0.0):
    """The Adasum pairwise operator (``adasum.h:331+``). Falls back to plain
    sum when either operand has zero norm (matching reference behavior of
    the ratio terms vanishing)."""
    af = a.astype(jnp.float32).ravel()
    bf = b.astype(jnp.float32).ravel()
    dot = jnp.dot(af, bf)
    na2 = jnp.dot(af, af)
    nb2 = jnp.dot(bf, bf)
    ca = jnp.where(na2 > eps, 1.0 - dot / (2.0 * jnp.where(na2 > eps, na2, 1.0)), 1.0)
    cb = jnp.where(nb2 > eps, 1.0 - dot / (2.0 * jnp.where(nb2 > eps, nb2, 1.0)), 1.0)
    out = af * ca + bf * cb
    return out.reshape(a.shape).astype(a.dtype)


def adasum_allreduce(x, axes):
    """Adasum-reduce ``x`` across the shards of ``axes`` (power-of-2 count).

    Tree schedule: at level l each shard exchanges its current vector with
    partner ``rank ^ 2**l`` and both compute the same combined result —
    the distance-doubling pairing of ``adasum.h:186-330`` with full-vector
    exchange instead of vector-halving (bandwidth traded for static shapes
    and zero host coordination; the tree and therefore the numerics are
    identical).
    """
    if isinstance(axes, str):
        axes = (axes,)
    if len(axes) > 1:
        # Hierarchical variant (adasum_cuda_operations.cc): sum-scatter
        # over the inner (ICI) axes, per-chunk Adasum across the cross-
        # slice axis, all-gather, divide by the inner size. The cross
        # axis is found BY NAME when the mesh has one (axis order must
        # not change which axis crosses slices); otherwise the first
        # axis plays that role.
        from horovod_tpu.parallel.mesh import DCN_AXIS
        dcn = DCN_AXIS if DCN_AXIS in axes else axes[0]
        return hierarchical_adasum_allreduce(
            x, ici_axes=tuple(a for a in axes if a != dcn), dcn_axis=dcn)
    axis = axes[0]
    size = lax.axis_size(axis)
    if size & (size - 1):
        raise ValueError(
            f"Adasum requires a power-of-2 number of shards, got {size} "
            "(same constraint as the reference, adasum.h)")
    levels = int(np.log2(size))
    me = lax.axis_index(axis)
    out = x
    for level in range(levels):
        d = 1 << level
        perm = [(i, i ^ d) for i in range(size)]
        other = lax.ppermute(out, axis, perm)
        # Order the operands canonically (lower rank first) so both partners
        # compute the identical combined vector.
        is_low = (me & d) == 0
        a = jnp.where(is_low, out, other)
        b = jnp.where(is_low, other, out)
        out = adasum_combine(a, b)
    return out


def hierarchical_adasum_allreduce(x, ici_axes, dcn_axis,
                                  divide_by_local_size=True):
    """The reference's production (2-level) Adasum composition
    (``adasum_cuda_operations.cc:96-260``): intra-node ReduceScatter (sum)
    → Adasum across nodes — run **independently per scattered chunk**,
    exactly like the reference, whose cross-node VHDD starts at
    ``start_level = local_size`` so each local rank's chunk gets its own
    combine coefficients — → intra-node Allgather, and finally the
    ``local_size`` division the reference applies in its framework layer
    (``torch/mpi_ops.py:104-110`` ``divisor = local_size()``; folded in
    here so every adapter sees the same user-visible result).

    TPU realization: ``psum_scatter`` over the ICI axes (zero-padded to
    equal shards — static shapes replace the reference's
    divisible-fusion-buffer constraint), the XOR-tree ``adasum_allreduce``
    over the DCN axis on the local chunk, ``all_gather`` back. The DCN
    axis size must be a power of 2 (reference: "non power of 2 nodes is
    not supported").
    """
    if isinstance(ici_axes, str):
        ici_axes = (ici_axes,)
    shape = x.shape
    flat = x.reshape(-1)
    n = flat.shape[0]
    ici_size = 1
    for a in ici_axes:
        ici_size *= lax.axis_size(a)
    if ici_size == 1:
        return adasum_allreduce(flat, (dcn_axis,)).reshape(shape)
    pad = (-n) % ici_size
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    shard = flat
    for a in ici_axes:
        shard = lax.psum_scatter(shard, a, scatter_dimension=0, tiled=True)
    shard = adasum_allreduce(shard, (dcn_axis,))
    out = shard
    for a in reversed(ici_axes):
        out = lax.all_gather(out, a, axis=0, tiled=True)
    out = out[:n].reshape(shape)
    if divide_by_local_size:
        if jnp.finfo(out.dtype).bits >= 32:
            out = out / ici_size  # native precision (f64 stays f64)
        else:  # fp16/bf16: divide in f32 like every other accumulation
            out = (out.astype(jnp.float32) / ici_size).astype(x.dtype)
    return out


def adasum_combine_np(a, b):
    """NumPy reference of the pairwise operator, for tests (pattern of
    ``test/test_adasum_tensorflow.py:33-63`` in the reference: reimplement
    the formula independently and compare)."""
    af = a.astype(np.float32).ravel()
    bf = b.astype(np.float32).ravel()
    dot = float(np.dot(af, bf))
    na2 = float(np.dot(af, af))
    nb2 = float(np.dot(bf, bf))
    ca = 1.0 - dot / (2.0 * na2) if na2 > 0 else 1.0
    cb = 1.0 - dot / (2.0 * nb2) if nb2 > 0 else 1.0
    return (af * ca + bf * cb).reshape(a.shape)


def adasum_tree_np(vectors):
    """NumPy reference of the full tree schedule over a power-of-2 list."""
    vecs = [np.asarray(v, dtype=np.float32) for v in vectors]
    size = len(vecs)
    assert size & (size - 1) == 0
    level = 0
    while (1 << level) < size:
        d = 1 << level
        nxt = list(vecs)
        for i in range(size):
            j = i ^ d
            a, b = (vecs[i], vecs[j]) if i < j else (vecs[j], vecs[i])
            nxt[i] = adasum_combine_np(a, b)
        vecs = nxt
        level += 1
    return vecs[0]


def hierarchical_adasum_np(grid):
    """NumPy reference of the 2-level composite for tests: ``grid`` is
    ``[n_nodes, local_size, n]`` per-rank gradients. Reproduces the TPU
    schedule exactly — node sums, zero-padded equal-chunk scatter,
    per-chunk Adasum tree across nodes, concatenate, divide by
    ``local_size`` — in f32."""
    grid = np.asarray(grid, np.float32)
    n_nodes, local_size, n = grid.shape
    node_sums = grid.sum(axis=1)
    pad = (-n) % local_size
    padded = np.pad(node_sums, ((0, 0), (0, pad)))
    chunks = padded.reshape(n_nodes, local_size, -1)
    out = np.concatenate([
        adasum_tree_np([chunks[c, l] for c in range(n_nodes)])
        for l in range(local_size)])
    return out[:n] / local_size
