"""Packaging (reference role: ``setup.py:379-523`` — the reference
compiles its C++ core as a CPython extension at install time; here the
host core is a plain shared library loaded via ctypes, so the build step
shells out to ``cxx/Makefile`` and ships ``libhvdcore.so`` as package
data. ``pip install .`` produces a wheel with the native core prebuilt;
source checkouts still lazy-build on first import (``_core.build``)."""

import os
import subprocess

from setuptools import setup
from setuptools.command.build_py import build_py

HERE = os.path.abspath(os.path.dirname(__file__))


class BuildWithNativeCore(build_py):
    def run(self):
        subprocess.check_call(
            ["make", "-C", os.path.join(HERE, "cxx"),
             "-j", str(os.cpu_count() or 2)])
        super().run()


setup(
    name="horovod_tpu",
    version="0.1.0",
    description=("TPU-native distributed training framework with "
                 "Horovod's capabilities (XLA collectives data plane, "
                 "C++ host core, MPI-free launcher)"),
    packages=["horovod_tpu", "horovod_tpu.analysis",
              "horovod_tpu.analysis.rules",
              "horovod_tpu.chaos",
              "horovod_tpu.ckpt", "horovod_tpu.data",
              "horovod_tpu.diag", "horovod_tpu.elastic",
              "horovod_tpu.jax", "horovod_tpu.models",
              "horovod_tpu.mxnet", "horovod_tpu.ops",
              "horovod_tpu.parallel", "horovod_tpu.run",
              "horovod_tpu.runtime", "horovod_tpu.serve",
              "horovod_tpu.spark", "horovod_tpu.telemetry",
              "horovod_tpu.tensorflow", "horovod_tpu.torch",
              "horovod_tpu.utils"],
    package_data={"horovod_tpu": ["lib/libhvdcore.so"]},
    include_package_data=True,
    python_requires=">=3.10",
    install_requires=["numpy", "jax", "flax", "optax"],
    extras_require={
        "torch": ["torch"],
        "dev": ["pytest", "cloudpickle"],
    },
    entry_points={
        "console_scripts": [
            "hvdrun = horovod_tpu.run.run:main",
            "hvd-doctor = horovod_tpu.diag.doctor:doctor_cli",
            "hvd-lint = horovod_tpu.analysis.cli:main",
            "hvd-serve = horovod_tpu.serve.cli:main",
        ],
    },
    cmdclass={"build_py": BuildWithNativeCore},
)
