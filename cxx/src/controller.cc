#include "hvd/controller.h"

#include <algorithm>

namespace hvd {

ControlPlane::ControlPlane(int rank, int size, std::string coord_host,
                           int control_port)
    : rank_(rank), size_(size), coord_host_(std::move(coord_host)),
      control_port_(control_port) {}

ControlPlane::~ControlPlane() = default;

Status ControlPlane::EnsureConnected() {
  if (size_ == 1) return Status::OK();
  if (is_coordinator()) {
    if (!server_) {
      server_ = std::make_unique<TcpServer>(control_port_);
      if (!server_->ok())
        return Status::Unknown("controller: failed to listen on port " +
                               std::to_string(control_port_));
      workers_.resize(size_);
      int connected = 0;
      while (connected < size_ - 1) {
        auto conn = server_->Accept(120.0);
        if (!conn)
          return Status::Unknown("controller: timeout waiting for workers");
        // first frame from a worker is its rank
        std::vector<uint8_t> hello;
        Status s = conn->RecvFrame(hello);
        if (!s.ok()) return s;
        Reader r(hello);
        int wrank = r.i32();
        if (wrank <= 0 || wrank >= size_)
          return Status::InvalidArgument("controller: bad hello rank");
        workers_[wrank] = std::move(conn);
        ++connected;
      }
    }
  } else if (!coord_) {
    coord_ = TcpConnection::Connect(coord_host_, control_port_, 120.0);
    if (!coord_)
      return Status::Unknown("controller: cannot reach coordinator at " +
                             coord_host_ + ":" +
                             std::to_string(control_port_));
    Writer w;
    w.i32(rank_);
    Status s = coord_->SendFrame(w.data());
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status ControlPlane::Initialize(const std::string& advertise_host,
                                int advertise_port, const TopoClaim& topo,
                                std::vector<PeerInfo>& roster,
                                uint8_t& agreed_gates) {
  Status s = EnsureConnected();
  if (!s.ok()) return s;
  // gather (host, data_port, topology claim) to rank 0, broadcast the
  // roster + the coordinator's agreed gates
  Writer mine;
  mine.str(advertise_host);
  mine.i32(advertise_port);
  mine.i32(topo.local_rank);
  mine.i32(topo.local_size);
  mine.i32(topo.cross_rank);
  mine.i32(topo.cross_size);
  mine.u8(topo.want_gates);
  std::vector<std::vector<uint8_t>> all;
  s = GatherFrames(mine.data(), all);
  if (!s.ok()) return s;
  std::vector<uint8_t> roster_bytes;
  if (is_coordinator()) {
    Writer w;
    // every rank's claim must describe the SAME contiguous partition
    // (rank = cross_rank * local_size + local_rank); any divergence —
    // a missing env var on one host, non-contiguous placement — turns
    // the hierarchical gates off for EVERYONE, never just for some.
    bool capable = size_ > 1;
    uint8_t want_and = 0x3;
    int L = -1, C = -1;
    for (int i = 0; i < size_; ++i) {
      Reader r(all[i]);
      w.str(r.str());
      w.i32(r.i32());
      TopoClaim c;
      c.local_rank = r.i32();
      c.local_size = r.i32();
      c.cross_rank = r.i32();
      c.cross_size = r.i32();
      c.want_gates = r.u8();
      want_and &= c.want_gates;
      if (i == 0) { L = c.local_size; C = c.cross_size; }
      if (c.local_size != L || c.cross_size != C || L < 2 || C < 2 ||
          L * C != size_ ||
          c.local_rank < 0 || c.local_rank >= L ||   // out-of-range claims
          c.cross_rank < 0 || c.cross_rank >= C ||   // can still satisfy
          i != c.cross_rank * c.local_size + c.local_rank)  // the identity
        capable = false;
    }
    uint8_t agreed = 0;
    if (capable) {
      agreed = kTopoCapable;
      if (want_and & 0x1) agreed |= kTopoHierAllreduce;
      if (want_and & 0x2) agreed |= kTopoHierAllgather;
    }
    w.u8(agreed);
    roster_bytes = w.take();
  }
  s = BcastFrame(roster_bytes, 0);
  if (!s.ok()) return s;
  roster.resize(size_);
  Reader r(roster_bytes);
  for (int i = 0; i < size_; ++i) {
    roster[i].host = r.str();
    roster[i].data_port = r.i32();
  }
  agreed_gates = r.u8();
  return Status::OK();
}

Status ControlPlane::GatherFrames(const std::vector<uint8_t>& mine,
                                  std::vector<std::vector<uint8_t>>& all) {
  if (size_ == 1) {
    all.assign(1, mine);
    return Status::OK();
  }
  if (is_coordinator()) {
    all.assign(size_, {});
    all[0] = mine;
    for (int i = 1; i < size_; ++i) {
      Status s = workers_[i]->RecvFrame(all[i]);
      if (!s.ok()) return s;
    }
    return Status::OK();
  }
  return coord_->SendFrame(mine);
}

Status ControlPlane::BcastFrame(std::vector<uint8_t>& bytes, int root) {
  if (size_ == 1) return Status::OK();
  // non-zero roots relay through the coordinator
  if (root != 0) {
    if (rank_ == root) {
      Status s = coord_->SendFrame(bytes);
      if (!s.ok()) return s;
    } else if (is_coordinator()) {
      Status s = workers_[root]->RecvFrame(bytes);
      if (!s.ok()) return s;
    }
    root = 0;
  }
  if (is_coordinator()) {
    for (int i = 1; i < size_; ++i) {
      Status s = workers_[i]->SendFrame(bytes);
      if (!s.ok()) return s;
    }
    return Status::OK();
  }
  return coord_->RecvFrame(bytes);
}

Status ControlPlane::SendReadyTensors(const RequestList& reqs) {
  Status s = EnsureConnected();
  if (!s.ok()) return s;
  auto bytes = reqs.Serialize();
  round_bytes_sent_ += static_cast<int64_t>(bytes.size()) + 4;
  return coord_->SendFrame(bytes);
}

Status ControlPlane::RecvFinalTensors(ResponseList& resp) {
  std::vector<uint8_t> buf;
  Status s = coord_->RecvFrame(buf);
  if (!s.ok()) return s;
  round_bytes_recv_ += static_cast<int64_t>(buf.size()) + 4;
  resp = ResponseList::Deserialize(buf);
  return Status::OK();
}

Status ControlPlane::RecvReadyTensors(std::vector<RequestList>& per_rank) {
  Status s = EnsureConnected();
  if (!s.ok()) return s;
  per_rank.assign(size_, {});
  for (int i = 1; i < size_; ++i) {
    std::vector<uint8_t> buf;
    s = workers_[i]->RecvFrame(buf);
    if (!s.ok()) return s;
    round_bytes_recv_ += static_cast<int64_t>(buf.size()) + 4;
    per_rank[i] = RequestList::Deserialize(buf);
  }
  return Status::OK();
}

Status ControlPlane::SendFinalTensors(const ResponseList& resp) {
  auto bytes = resp.Serialize();
  round_bytes_sent_ +=
      (static_cast<int64_t>(bytes.size()) + 4) * (size_ - 1);
  for (int i = 1; i < size_; ++i) {
    Status s = workers_[i]->SendFrame(bytes);
    if (!s.ok()) return s;
  }
  return Status::OK();
}

Status ControlPlane::Bcast(std::vector<uint8_t>& bytes, int root) {
  std::lock_guard<std::mutex> lock(mu_);
  Status s = EnsureConnected();
  if (!s.ok()) return s;
  return BcastFrame(bytes, root);
}

Status ControlPlane::Barrier() {
  std::lock_guard<std::mutex> lock(mu_);
  Status s = EnsureConnected();
  if (!s.ok()) return s;
  std::vector<std::vector<uint8_t>> all;
  s = GatherFrames({}, all);
  if (!s.ok()) return s;
  std::vector<uint8_t> empty;
  return BcastFrame(empty, 0);
}

Status ControlPlane::BitAllreduce(std::vector<uint64_t>& bits, bool is_and) {
  std::lock_guard<std::mutex> lock(mu_);
  Status s = EnsureConnected();
  if (!s.ok()) return s;
  std::vector<uint8_t> mine(bits.size() * 8);
  std::copy(reinterpret_cast<uint8_t*>(bits.data()),
            reinterpret_cast<uint8_t*>(bits.data()) + mine.size(),
            mine.begin());
  std::vector<std::vector<uint8_t>> all;
  s = GatherFrames(mine, all);
  if (!s.ok()) return s;
  std::vector<uint8_t> result = mine;
  if (is_coordinator()) {
    for (int i = 1; i < size_; ++i) {
      const uint64_t* other =
          reinterpret_cast<const uint64_t*>(all[i].data());
      uint64_t* acc = reinterpret_cast<uint64_t*>(result.data());
      size_t n = std::min(all[i].size(), result.size()) / 8;
      for (size_t j = 0; j < n; ++j)
        acc[j] = is_and ? (acc[j] & other[j]) : (acc[j] | other[j]);
    }
  }
  s = BcastFrame(result, 0);
  if (!s.ok()) return s;
  std::copy(result.data(), result.data() + result.size(),
            reinterpret_cast<uint8_t*>(bits.data()));
  return Status::OK();
}

}  // namespace hvd
