#include "hvd/logging.h"
#include "hvd/operations.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>

#include "hvd/controller.h"
#include "hvd/cpu_ops.h"
#include "hvd/negotiator.h"
#include "hvd/parameter_manager.h"
#include "hvd/peer_mesh.h"
#include "hvd/response_cache.h"
#include "hvd/stall_inspector.h"
#include "hvd/tensor_queue.h"
#include "hvd/timeline.h"

namespace hvd {
namespace {

// ---- handle manager (reference: torch/handle_manager.{h,cc}) -----------

struct HandleState {
  bool done = false;
  Status status;
  std::vector<uint8_t> output;
};

class HandleManager {
 public:
  int Allocate() {
    std::lock_guard<std::mutex> lock(mu_);
    int h = next_++;
    states_[h];
    return h;
  }
  void MarkDone(int h, Status s, std::vector<uint8_t> output = {}) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = states_.find(h);
      if (it == states_.end()) return;
      it->second.done = true;
      it->second.status = std::move(s);
      it->second.output = std::move(output);
    }
    cv_.notify_all();
  }
  // 0 pending, 1 ok, -1 error, -2 unknown handle
  int Poll(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end()) return -2;
    if (!it->second.done) return 0;
    return it->second.status.ok() ? 1 : -1;
  }
  int Wait(int h) {
    std::unique_lock<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end()) return -2;
    cv_.wait(lock, [&] { return states_.at(h).done; });
    return states_.at(h).status.ok() ? 1 : -1;
  }
  std::string ErrorMessage(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    return it == states_.end() ? "unknown handle" : it->second.status.reason();
  }
  int64_t OutputSize(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end() || !it->second.done) return -1;
    return static_cast<int64_t>(it->second.output.size());
  }
  bool CopyOutput(int h, void* dst) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end() || !it->second.done) return false;
    std::memcpy(dst, it->second.output.data(), it->second.output.size());
    return true;
  }
  void Release(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    states_.erase(h);
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, HandleState> states_;
  int next_ = 0;
};

// ---- global state (reference: common/global_state.h) -------------------

struct Global {
  int rank = 0;
  int size = 1;
  // host placement (HOROVOD_LOCAL_*/CROSS_* launcher contract) + the
  // hierarchical-collective gates. The gates and `hier_capable` are
  // COORDINATOR-AGREED at the roster handshake (never per-rank env
  // decisions — a split decision would run mismatched ring schedules and
  // deadlock the data plane); the autotuner may flip the allreduce gate
  // as a categorical dimension when capable.
  Topology topo;
  bool hier_capable = false;
  bool hierarchical_allreduce = false;
  bool hierarchical_allgather = false;
  std::unique_ptr<ControlPlane> control;
  std::unique_ptr<PeerMesh> mesh;
  TensorQueue queue;
  HandleManager handles;
  Negotiator negotiator{1};
  ResponseCache cache;
  StallInspector stall;
  Timeline timeline;

  std::thread loop_thread;
  std::atomic<bool> shutdown_requested{false};
  std::atomic<bool> initialized{false};
  double cycle_time_ms = 1.0;
  int64_t fusion_threshold = 64 * 1024 * 1024;
  // response-cache gate: env-initialized, autotuner-flippable. Every rank
  // applies the same value in the same cycle (it rides the ResponseList),
  // which keeps the cache replicas in lockstep through flips.
  bool cache_enabled = true;
  // payload bytes the data plane moved this cycle (ALL op types — the
  // autotuner's score numerator; reference parameter_manager scores
  // allreduce+allgather+broadcast traffic alike)
  std::atomic<int64_t> cycle_bytes{0};
  // host-side memcpy accounting (enqueue copy-in, fusion staging, output
  // copy-out) — the zero-copy borrow path exists to keep this at 0 for
  // large single tensors; tests assert on it
  std::atomic<int64_t> copied_bytes{0};

  // autotuner (coordinator scores cycles + proposes; tuned params ride
  // the ResponseList to workers — reference SynchronizeParameters).
  // tune_mu guards pm + the tuned fusion_threshold/cycle_time_ms pair
  // against user-thread introspection racing the loop thread.
  ParameterManager pm;
  std::mutex tune_mu;
  std::chrono::steady_clock::time_point last_cycle_tp;

  // join state
  std::vector<bool> joined_ranks;     // coordinator
  int last_joiner = -1;  // coordinator: rank whose join completed the set
  bool self_joined = false;
  int join_handle = -1;
  std::mutex join_mu;

  // steady-state cache protocol: every rank keeps an IDENTICAL cache
  // replica (driven by the broadcast ResponseList), announces repeat
  // tensors as bits, and reconstructs hit responses locally.
  std::unordered_map<std::string, Request> negotiating;    // full requests
  std::unordered_map<std::string, Request> cache_pending;  // bit-announced
  // coordinator watchdog: first time a bit was seen set by only a subset
  // of ranks (a stale hit must eventually renegotiate via the full path
  // so the stall inspector can see it)
  std::unordered_map<uint32_t, std::chrono::steady_clock::time_point>
      partial_bits;
  double cache_stall_sec = 60.0;

  std::string last_error;
};

Global* g = nullptr;

int JoinedCount() {
  int c = 0;
  for (bool b : g->joined_ranks)
    if (b) c += 1;
  return c;
}

// ---- execution (reference: PerformOperation, operations.cc:227-304) ----

void CompleteEntry(TensorTableEntry& e, const Status& s) {
  if (e.handle >= 0)
    g->handles.MarkDone(e.handle, s, std::move(e.data));
}

// Input/in-place-result pointer: the borrowed caller buffer when the
// entry was enqueued zero-copy, the owned staging vector otherwise.
uint8_t* EntryPtr(TensorTableEntry& e) {
  return e.ext != nullptr ? e.ext : e.data.data();
}

// The reduction schedule itself, shared by the fused and single-tensor
// paths: runs in place on `buf`.
Status RunAllreduce(Response::Type type, uint8_t* buf, int64_t total,
                    DataType dtype, ReduceOp op, int active) {
  if (type == Response::ADASUM) {
    // like the reference, Adasum goes hierarchical whenever the agreed
    // topology is a real 2-level split (GPU Adasum is ALWAYS the
    // RS->Adasum->AG composite in the reference, not gated by the
    // allreduce autotune knob); flat XOR-tree otherwise
    if (g->hier_capable && g->topo.hierarchical() &&
        (g->topo.cross_size & (g->topo.cross_size - 1)) == 0)
      return HierarchicalAdasumAllreduce(*g->mesh, g->topo, buf, total,
                                         dtype);
    return AdasumAllreduce(*g->mesh, *g->control, g->rank, g->size, buf,
                           total, dtype);
  }
  // AVERAGE divides by the number of *contributing* (non-joined) ranks
  if (g->hierarchical_allreduce)  // coordinator-agreed at init, never split
    return HierarchicalAllreduce(*g->mesh, g->topo, buf, total, dtype, op,
                                 active);
  ReduceOp wire_op = (op == ReduceOp::AVERAGE) ? ReduceOp::SUM : op;
  Status st = RingAllreduce(*g->mesh, g->rank, g->size, buf, total, dtype,
                            wire_op);
  if (st.ok() && op == ReduceOp::AVERAGE)
    ScaleInPlace(buf, total, dtype, 1.0 / active);
  return st;
}

void ExecuteFusedAllreduce(const Response& resp) {
  size_t esz = DataTypeSize(resp.dtype);
  int64_t total = 0;
  for (int64_t c : resp.tensor_sizes) total += c;

  std::vector<TensorTableEntry> entries(resp.tensor_names.size());
  std::vector<bool> have(resp.tensor_names.size(), false);
  for (size_t i = 0; i < resp.tensor_names.size(); ++i)
    have[i] = g->queue.Take(resp.tensor_names[i], entries[i]);

  ReduceOp op = static_cast<ReduceOp>(resp.reduce_op);
  int active = resp.active_ranks > 0 ? resp.active_ranks : g->size;
  g->cycle_bytes.fetch_add(total * static_cast<int64_t>(esz));
  const char* activity = resp.type == Response::ADASUM
                             ? "ADASUM_ALLREDUCE" : "RING_ALLREDUCE";

  // single-tensor fast path: reduce in place on the entry's own buffer
  // (for a borrowed buffer that is the caller's memory — zero host
  // copies, the role of the reference's zero-copy tensor wrap)
  if (entries.size() == 1 && have[0]) {
    TensorTableEntry& e = entries[0];
    uint8_t* buf = EntryPtr(e);
    if (e.prescale != 1.0)
      ScaleInPlace(buf, total, resp.dtype, e.prescale);
    g->timeline.ActivityStart(resp.tensor_names[0], activity);
    Status st = RunAllreduce(resp.type, buf, total, resp.dtype, op, active);
    g->timeline.ActivityEnd(resp.tensor_names[0]);
    if (st.ok() && e.postscale != 1.0)
      ScaleInPlace(buf, total, resp.dtype, e.postscale);
    CompleteEntry(e, st);
    return;
  }

  // fusion buffer (reference FusionBufferManager + MemcpyInFusionBuffer) —
  // joined ranks contribute zeros (reference tensor_queue.h:39-41)
  std::vector<uint8_t> fused(total * esz, 0);
  int64_t off = 0;
  for (size_t i = 0; i < entries.size(); ++i) {
    int64_t nbytes = resp.tensor_sizes[i] * esz;
    if (have[i]) {
      uint8_t* src = EntryPtr(entries[i]);
      std::memcpy(fused.data() + off, src, nbytes);
      // prescale inside the fusion buffer, never in the source: a
      // borrowed caller tensor must stay untouched if the ring fails
      if (entries[i].prescale != 1.0)
        ScaleInPlace(fused.data() + off, resp.tensor_sizes[i], resp.dtype,
                     entries[i].prescale);
      g->copied_bytes.fetch_add(nbytes);
    }
    off += nbytes;
  }

  g->timeline.ActivityStart(resp.tensor_names[0], activity);
  Status st = RunAllreduce(resp.type, fused.data(), total, resp.dtype, op,
                           active);
  g->timeline.ActivityEnd(resp.tensor_names[0]);

  off = 0;
  for (size_t i = 0; i < entries.size(); ++i) {
    int64_t nbytes = resp.tensor_sizes[i] * esz;
    if (have[i]) {
      // on failure the fusion buffer holds partially-reduced garbage —
      // leave the entry (especially a borrowed caller tensor) untouched
      if (st.ok()) {
        uint8_t* dst = EntryPtr(entries[i]);
        std::memcpy(dst, fused.data() + off, nbytes);
        g->copied_bytes.fetch_add(nbytes);
        if (entries[i].postscale != 1.0)
          ScaleInPlace(dst, resp.tensor_sizes[i], resp.dtype,
                       entries[i].postscale);
      }
      CompleteEntry(entries[i], st);
    }
    off += nbytes;
  }
}

void ExecuteAllgather(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;  // joined: no-op
  size_t esz = DataTypeSize(resp.dtype);
  int64_t row = 1;
  for (int d = 1; d < e.shape.ndim(); ++d) row *= e.shape.dim(d);
  std::vector<int64_t> counts;
  int64_t total = 0;
  for (int64_t dim0 : resp.tensor_sizes) {
    counts.push_back(dim0 * row);
    total += dim0 * row;
  }
  std::vector<uint8_t> out(total * esz);
  g->cycle_bytes.fetch_add(total * static_cast<int64_t>(esz));
  Status st;
  if (g->hierarchical_allgather) {  // coordinator-agreed at init
    g->timeline.ActivityStart(e.name, "HIER_ALLGATHER");
    st = HierarchicalAllgatherv(*g->mesh, g->topo, EntryPtr(e), counts,
                                resp.dtype, out.data());
  } else {
    g->timeline.ActivityStart(e.name, "RING_ALLGATHER");
    st = RingAllgatherv(*g->mesh, g->rank, g->size, EntryPtr(e),
                        counts, resp.dtype, out.data());
  }
  g->timeline.ActivityEnd(e.name);
  e.data = std::move(out);
  CompleteEntry(e, st);
}

void ExecuteBroadcast(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;
  int64_t bc_bytes =
      resp.tensor_sizes[0] * static_cast<int64_t>(DataTypeSize(resp.dtype));
  g->cycle_bytes.fetch_add(bc_bytes);
  g->timeline.ActivityStart(e.name, "BROADCAST");
  Status st = Broadcast(*g->mesh, g->rank, g->size, EntryPtr(e),
                        resp.tensor_sizes[0], resp.dtype, e.root_rank);
  g->timeline.ActivityEnd(e.name);
  CompleteEntry(e, st);
}

void ExecuteAlltoall(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;
  int64_t count = resp.tensor_sizes[0];
  Status st;
  if (count % g->size != 0) {
    st = Status::InvalidArgument(
        "alltoall requires first dim divisible by size");
    CompleteEntry(e, st);
    return;
  }
  int64_t nbytes =
      e.shape.num_elements() * static_cast<int64_t>(DataTypeSize(resp.dtype));
  std::vector<uint8_t> out(nbytes);
  g->cycle_bytes.fetch_add(nbytes);
  g->timeline.ActivityStart(e.name, "ALLTOALL");
  st = AllToAll(*g->mesh, g->rank, g->size, EntryPtr(e), count / g->size,
                resp.dtype, out.data());
  g->timeline.ActivityEnd(e.name);
  e.data = std::move(out);
  CompleteEntry(e, st);
}

void ExecuteReduceScatter(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;
  size_t esz = DataTypeSize(resp.dtype);
  // split along dim 0 like the compiled path (lax.psum_scatter on dim 0):
  // rank i gets rows [i*base + min(i, rem), ...) — remainder rows go to
  // the first `rem` ranks
  int64_t d0 = e.shape.ndim() > 0 ? e.shape.dim(0) : 1;
  int64_t row = 1;
  for (int d = 1; d < e.shape.ndim(); ++d) row *= e.shape.dim(d);
  std::vector<int64_t> counts(g->size);
  int64_t base = d0 / g->size, rem = d0 % g->size;
  for (int i = 0; i < g->size; ++i)
    counts[i] = (base + (i < rem ? 1 : 0)) * row;

  if (e.prescale != 1.0)
    ScaleInPlace(EntryPtr(e), e.shape.num_elements(), resp.dtype,
                 e.prescale);
  ReduceOp op = static_cast<ReduceOp>(resp.reduce_op);
  ReduceOp wire_op = (op == ReduceOp::AVERAGE) ? ReduceOp::SUM : op;
  std::vector<uint8_t> out(counts[g->rank] * esz);
  g->cycle_bytes.fetch_add(
      e.shape.num_elements() * static_cast<int64_t>(esz));
  g->timeline.ActivityStart(e.name, "RING_REDUCESCATTER");
  // the input buffer is clobbered as ring scratch (borrowed buffers too —
  // in-place reduce-scatter semantics)
  Status st = RingReduceScatter(*g->mesh, g->rank, g->size, EntryPtr(e),
                                counts, resp.dtype, wire_op, out.data());
  g->timeline.ActivityEnd(e.name);
  if (st.ok() && op == ReduceOp::AVERAGE) {
    int active = resp.active_ranks > 0 ? resp.active_ranks : g->size;
    ScaleInPlace(out.data(), counts[g->rank], resp.dtype, 1.0 / active);
  }
  if (st.ok() && e.postscale != 1.0)
    ScaleInPlace(out.data(), counts[g->rank], resp.dtype, e.postscale);
  e.data = std::move(out);
  CompleteEntry(e, st);
}

void ExecuteBarrier(const Response& resp) {
  TensorTableEntry e;
  bool have = g->queue.Take(resp.tensor_names[0], e);
  uint8_t one = 1;
  Status st = RingAllreduce(*g->mesh, g->rank, g->size, &one, 1,
                            DataType::UINT8, ReduceOp::MAX);
  if (have) CompleteEntry(e, st);
}

void ExecuteError(const Response& resp) {
  for (const auto& name : resp.tensor_names) {
    TensorTableEntry e;
    if (g->queue.Take(name, e))
      CompleteEntry(e, Status::InvalidArgument(resp.error_message));
  }
}

void ExecuteResponse(const Response& resp) {
  switch (resp.type) {
    case Response::ALLREDUCE:
    case Response::ADASUM:
      ExecuteFusedAllreduce(resp);
      break;
    case Response::ALLGATHER:
      ExecuteAllgather(resp);
      break;
    case Response::BROADCAST:
      ExecuteBroadcast(resp);
      break;
    case Response::ALLTOALL:
      ExecuteAlltoall(resp);
      break;
    case Response::REDUCESCATTER:
      ExecuteReduceScatter(resp);
      break;
    case Response::BARRIER:
      ExecuteBarrier(resp);
      break;
    case Response::JOIN: {
      std::lock_guard<std::mutex> lock(g->join_mu);
      if (g->join_handle >= 0) {
        // payload: the last-joined rank as int32 (hvd.join's return)
        int32_t last = resp.tensor_sizes.empty()
                           ? -1
                           : static_cast<int32_t>(resp.tensor_sizes[0]);
        std::vector<uint8_t> out(sizeof(last));
        std::memcpy(out.data(), &last, sizeof(last));
        g->handles.MarkDone(g->join_handle, Status::OK(), std::move(out));
        g->join_handle = -1;
      }
      g->self_joined = false;
      std::fill(g->joined_ranks.begin(), g->joined_ranks.end(), false);
      break;
    }
    case Response::ERROR:
      ExecuteError(resp);
      break;
  }
}

// ---- negotiation cycle (reference: RunLoopOnce + ComputeResponseList) --

ResponseList CoordinatorNegotiate(std::vector<RequestList>& per_rank) {
  ResponseList rl;
  bool any_shutdown = false;
  bool join_changed = false;
  std::vector<std::string> ready;
  std::unordered_set<std::string> seen;

  for (int r = 0; r < g->size; ++r) {
    if (per_rank[r].shutdown) any_shutdown = true;
    std::vector<Request> normal;
    for (auto& q : per_rank[r].requests) {
      if (q.type == Request::JOIN) {
        if (!g->joined_ranks[r]) {
          g->joined_ranks[r] = true;
          g->last_joiner = r;
          join_changed = true;
        }
      } else {
        normal.push_back(std::move(q));
      }
    }
    for (const auto& name :
         g->negotiator.AddRequests(normal, JoinedCount()))
      if (seen.insert(name).second) ready.push_back(name);
  }
  if (join_changed) {
    for (const auto& name : g->negotiator.ReadyAfterJoin(JoinedCount()))
      if (seen.insert(name).second) ready.push_back(name);
  }

  // cache-bit coordination (reference CacheCoordinator::sync,
  // response_cache.h:107-167): a bit survives only when every non-joined
  // rank announced it; a full request for a cached name orders a global
  // eviction (that rank's parameters changed). Joined ranks contribute
  // implicit all-ones (they zero-fill every tensor).
  if (g->size > 1) {
    std::unordered_set<uint32_t> invalid;
    for (int r = 0; r < g->size; ++r)
      for (const auto& q : per_rank[r].requests)
        if (q.type != Request::JOIN &&
            g->cache.Cached(q) != ResponseCache::CacheState::MISS)
          invalid.insert(g->cache.GetBit(q.tensor_name));

    size_t words = g->cache.NumBitWords();
    std::vector<uint64_t> all_and(words, ~uint64_t{0});
    std::vector<uint64_t> any_or(words, 0);
    int contributors = 0;
    for (int r = 0; r < g->size; ++r) {
      if (g->joined_ranks[r]) continue;
      ++contributors;
      const auto& bits = per_rank[r].cache_bits;
      for (size_t w = 0; w < words; ++w) {
        uint64_t b = w < bits.size() ? bits[w] : 0;
        all_and[w] &= b;
        any_or[w] |= b;
      }
    }
    if (contributors == 0) all_and.assign(words, 0);

    // cache tuned off: a worker one cycle behind the flip may still
    // announce bits — evict them immediately so its pending tensors
    // renegotiate in full next cycle instead of waiting out the
    // stale-bit watchdog
    if (!g->cache_enabled) {
      for (size_t w = 0; w < words; ++w) {
        for (uint64_t word = any_or[w]; word;) {
          int b = __builtin_ctzll(word);
          word &= word - 1;
          invalid.insert(static_cast<uint32_t>(w * 64 + b));
        }
      }
      all_and.assign(words, 0);
    }

    // stale-hit watchdog: a bit some (not all) ranks keep announcing
    // must eventually renegotiate in full so the stall inspector can
    // name the missing ranks
    auto now = std::chrono::steady_clock::now();
    std::unordered_set<uint32_t> partial_now;
    for (size_t w = 0; w < words; ++w) {
      for (uint64_t word = any_or[w] & ~all_and[w]; word;) {
        int b = __builtin_ctzll(word);
        word &= word - 1;
        partial_now.insert(static_cast<uint32_t>(w * 64 + b));
      }
    }
    for (auto it = g->partial_bits.begin(); it != g->partial_bits.end();) {
      if (!partial_now.count(it->first)) {
        it = g->partial_bits.erase(it);
      } else if (std::chrono::duration<double>(now - it->second).count() >
                 g->cache_stall_sec) {
        invalid.insert(it->first);
        it = g->partial_bits.erase(it);
      } else {
        ++it;
      }
    }
    for (uint32_t b : partial_now)
      if (!invalid.count(b)) g->partial_bits.emplace(b, now);

    // joined ranks cannot satisfy broadcast/alltoall/reducescatter even
    // on the hit path — force those back through the full path, which
    // produces the proper ERROR response (guard below at the
    // ready-tensor loop)
    if (JoinedCount() > 0) {
      for (size_t w = 0; w < words; ++w) {
        for (uint64_t word = all_and[w]; word;) {
          int b = __builtin_ctzll(word);
          word &= word - 1;
          uint32_t bit = static_cast<uint32_t>(w * 64 + b);
          Response::Type t = g->cache.TypeForBit(bit);
          if (t == Response::BROADCAST || t == Response::ALLTOALL ||
              t == Response::REDUCESCATTER)
            invalid.insert(bit);
        }
      }
    }

    for (uint32_t b : invalid)
      if (b / 64 < words) all_and[b / 64] &= ~(uint64_t{1} << (b % 64));

    rl.cache_hits = std::move(all_and);
    rl.cache_invalid.assign(invalid.begin(), invalid.end());
    std::sort(rl.cache_invalid.begin(), rl.cache_invalid.end());
  }

  int active = g->size - JoinedCount();
  rl.active_ranks = active;
  for (const auto& name : ready) {
    g->timeline.NegotiateEnd(name);
    Response r = g->negotiator.BuildResponse(name);
    r.active_ranks = active;
    // allgather/broadcast/alltoall cannot zero-fill for joined ranks
    // (reference restriction, controller.cc:443-447,523-527)
    if (active < g->size &&
        (r.type == Response::ALLGATHER || r.type == Response::BROADCAST ||
         r.type == Response::ALLTOALL ||
         r.type == Response::REDUCESCATTER)) {
      r.error_message = "tensor " + r.tensor_names[0] +
                        ": allgather/broadcast/alltoall/reducescatter are "
                        "not supported after a rank has joined";
      r.type = Response::ERROR;
    }
    rl.responses.push_back(std::move(r));
  }
  rl.responses = Negotiator::Fuse(std::move(rl.responses),
                                  g->fusion_threshold);

  // all ranks joined -> emit JOIN response (reference controller.cc:290)
  if (g->size > 0 && JoinedCount() == g->size)
    rl.responses.push_back([] {
      Response r;
      r.type = Response::JOIN;
      r.tensor_names = {"join.noname"};
      // reference hvd.join() returns the rank that joined LAST — ride
      // it in tensor_sizes so every rank learns it
      r.tensor_sizes = {g->last_joiner};
      return r;
    }());

  if (g->stall.Check(g->negotiator.Pending(), g->size)) any_shutdown = true;
  rl.shutdown = any_shutdown;

  // While tuning (and after convergence), every cycle's ResponseList
  // carries the coordinator's current proposal so all ranks run the
  // same (fusion threshold, cycle time).
  if (g->pm.enabled()) {
    std::lock_guard<std::mutex> lock(g->tune_mu);
    rl.has_tuned_params = true;
    rl.tuned_fusion_threshold = g->pm.fusion_threshold();
    rl.tuned_cycle_time_ms = g->pm.cycle_time_ms();
    rl.tuned_hierarchical = g->pm.hierarchical() ? 1 : 0;
    rl.tuned_cache = g->pm.cache_enabled() ? 1 : 0;
    g->fusion_threshold = g->pm.fusion_threshold();
    g->cycle_time_ms = g->pm.cycle_time_ms();
    g->hierarchical_allreduce = g->pm.hierarchical() && g->hier_capable;
    g->cache_enabled = g->pm.cache_enabled();
  }
  return rl;
}

bool IsCacheable(Response::Type t) {
  // allgather embeds per-rank dims that change step to step; barrier
  // names are unique per call; join/error are control outcomes
  return t == Response::ALLREDUCE || t == Response::ADASUM ||
         t == Response::BROADCAST || t == Response::ALLTOALL ||
         t == Response::REDUCESCATTER;
}

// Every rank applies the SAME cache mutations in the SAME order, keyed
// off the broadcast ResponseList — that is what keeps the replicas
// identical without ever shipping cache state (reference keeps replicas
// in sync the same way, via the deterministic response stream).
// Returns the ordered execution list: reconstructed cache hits first
// (re-fused locally), then the full responses.
std::vector<Response> BuildExecutionList(ResponseList& rl) {
  std::vector<Response> exec;
  if (g->size > 1) {
    // 1. evictions (a rank's params changed, or a stale partial hit)
    for (uint32_t bit : rl.cache_invalid) {
      std::string name = g->cache.NameForBit(bit);
      if (name.empty()) continue;
      auto it = g->cache_pending.find(name);
      if (it != g->cache_pending.end()) {
        g->queue.Requeue(it->second);  // renegotiate in full next cycle
        g->cache_pending.erase(it);
      }
      g->cache.Erase(name);
    }
    // 2. agreed hits, reconstructed from the local replica in bit order
    std::vector<Response> hits = g->cache.ResponsesForBits(rl.cache_hits);
    for (auto& r : hits) {
      g->cache.Get(r.tensor_names[0]);  // LRU touch, replica-identical
      g->cache_pending.erase(r.tensor_names[0]);
      r.active_ranks = rl.active_ranks > 0 ? rl.active_ranks : g->size;
    }
    hits = Negotiator::Fuse(std::move(hits), g->fusion_threshold);
    for (auto& r : hits) exec.push_back(std::move(r));
  }
  // 3. full responses seed the replica for future hit cycles
  for (Response& r : rl.responses) {
    // replica Put is gated on the SAME tuned cache flag on every rank
    // (adopted from this cycle's ResponseList), so flips keep replicas
    // identical
    if (g->size > 1 && g->cache_enabled && r.error_message.empty() &&
        IsCacheable(r.type) && r.type != Response::BARRIER) {
      for (size_t i = 0; i < r.tensor_names.size(); ++i) {
        const std::string& name = r.tensor_names[i];
        Response single;
        single.type = r.type;
        single.tensor_names = {name};
        single.dtype = r.dtype;
        single.reduce_op = r.reduce_op;
        single.tensor_sizes =
            (r.type == Response::ALLREDUCE || r.type == Response::ADASUM)
                ? std::vector<int64_t>{r.tensor_sizes[i]}
                : r.tensor_sizes;
        // Put must run on EVERY rank (a joined rank has no local request
        // for this tensor but its replica's bit/LRU sequence must still
        // match everyone else's). Without the real request, synthesize
        // flat-shape params: the rank's next real request then reads as
        // INVALID and triggers one clean renegotiation.
        Request params;
        auto it = g->negotiating.find(name);
        if (it != g->negotiating.end()) {
          params = it->second;
        } else {
          params.type = static_cast<Request::Type>(r.type);
          params.tensor_name = name;
          params.dtype = r.dtype;
          params.reduce_op = r.reduce_op;
          params.shape = TensorShape({single.tensor_sizes[0]});
        }
        std::string evicted = g->cache.Put(params, single);
        if (!evicted.empty()) {
          // capacity eviction of a tensor some rank may have announced
          // via bits: requeue ours if pending so it renegotiates
          auto pit = g->cache_pending.find(evicted);
          if (pit != g->cache_pending.end()) {
            g->queue.Requeue(pit->second);
            g->cache_pending.erase(pit);
          }
        }
      }
    }
    for (const auto& name : r.tensor_names) g->negotiating.erase(name);
    exec.push_back(std::move(r));
  }
  return exec;
}

bool RunLoopOnce() {
  RequestList mine;
  auto popped = g->queue.PopRequests();
  for (auto& q : popped) {
    // steady-state split: identical-parameter repeats are announced as
    // a cache bit; everything else goes the full negotiation path
    if (g->size > 1 && g->cache_enabled && q.type != Request::BARRIER &&
        g->cache.Cached(q) == ResponseCache::CacheState::HIT) {
      g->cache_pending.emplace(q.tensor_name, q);
      continue;
    }
    if (g->size > 1) g->negotiating[q.tensor_name] = q;
    g->timeline.NegotiateStart(q.tensor_name, RequestTypeName(q.type));
    mine.requests.push_back(std::move(q));
  }
  if (g->size > 1 && !g->cache_pending.empty()) {
    std::vector<std::string> names;
    names.reserve(g->cache_pending.size());
    for (const auto& kv : g->cache_pending) names.push_back(kv.first);
    mine.cache_bits = g->cache.PackBits(names);
  }
  {
    std::lock_guard<std::mutex> lock(g->join_mu);
    if (g->self_joined) {
      Request jq;
      jq.type = Request::JOIN;
      jq.request_rank = g->rank;
      mine.requests.push_back(jq);
      g->self_joined = false;  // announce once
    }
  }
  mine.shutdown = g->shutdown_requested.load();

  ResponseList rl;
  if (g->size == 1) {
    std::vector<RequestList> per_rank{mine};
    rl = CoordinatorNegotiate(per_rank);
  } else if (g->control->is_coordinator()) {
    std::vector<RequestList> per_rank;
    Status s = g->control->RecvReadyTensors(per_rank);
    if (!s.ok()) return false;
    per_rank[0] = std::move(mine);
    rl = CoordinatorNegotiate(per_rank);
    s = g->control->SendFinalTensors(rl);
    if (!s.ok()) return false;
  } else {
    Status s = g->control->SendReadyTensors(mine);
    if (!s.ok()) return false;
    s = g->control->RecvFinalTensors(rl);
    if (!s.ok()) return false;
    if (rl.has_tuned_params) {  // adopt the coordinator's tuned values
      std::lock_guard<std::mutex> lock(g->tune_mu);
      g->fusion_threshold = rl.tuned_fusion_threshold;
      g->cycle_time_ms = rl.tuned_cycle_time_ms;
      g->hierarchical_allreduce =
          rl.tuned_hierarchical != 0 && g->hier_capable;
      g->cache_enabled = rl.tuned_cache != 0;
    }
  }

  std::vector<Response> exec = BuildExecutionList(rl);
  for (const auto& resp : exec) {
    g->timeline.Start(resp.tensor_names[0],
                      std::string("OP_") + std::to_string(resp.type));
    ExecuteResponse(resp);
    g->timeline.End(resp.tensor_names[0]);
  }
  g->timeline.MarkCycle();

  // Coordinator scores the cycle (bytes moved / wall time incl. the
  // previous sleep) and advances the Bayesian-opt proposal loop. Idle
  // cycles are not scored — a pause between bursts of work must not
  // poison the throughput estimate.
  if (g->pm.active()) {
    auto now = std::chrono::steady_clock::now();
    double elapsed =
        std::chrono::duration<double>(now - g->last_cycle_tp).count();
    g->last_cycle_tp = now;
    int64_t bytes = g->cycle_bytes.exchange(0);
    if (bytes > 0) {
      std::lock_guard<std::mutex> lock(g->tune_mu);
      g->pm.Update(bytes, elapsed);
    }
  }
  return !rl.shutdown;
}

void BackgroundLoop() {
  while (RunLoopOnce()) {
    std::this_thread::sleep_for(
        std::chrono::duration<double, std::milli>(g->cycle_time_ms));
  }
  // fail anything still pending (reference SHUT_DOWN_ERROR)
  for (auto& e : g->queue.DrainAll())
    CompleteEntry(e, Status::Aborted(
        "horovod_tpu core shut down before this op completed"));
  {
    std::lock_guard<std::mutex> lock(g->join_mu);
    if (g->join_handle >= 0) {
      g->handles.MarkDone(g->join_handle, Status::Aborted("shutdown"));
      g->join_handle = -1;
    }
  }
}

}  // namespace
}  // namespace hvd

// ---- C API -------------------------------------------------------------

using namespace hvd;

int hvdc_init(int rank, int size, const char* coord_host, int coord_port,
              const char* advertise_host) {
  if (g != nullptr && g->initialized.load()) return 0;
  if (g != nullptr) {  // re-init after shutdown
    delete g;
    g = nullptr;
  }
  auto* ng = new Global();
  hvd::logging::config().rank.store(rank);
  HVD_LOG(INFO) << "initializing host core: rank " << rank << "/" << size;
  ng->rank = rank;
  ng->size = size;
  ng->negotiator = Negotiator(size);
  ng->joined_ranks.assign(size, false);
  ng->cycle_time_ms = EnvDouble("HOROVOD_CYCLE_TIME", 1.0);
  ng->fusion_threshold =
      EnvInt("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024);

  // host placement: the hvdrun launcher exports LOCAL_*/CROSS_* with
  // contiguous per-host ranks; absent or inconsistent values degrade to a
  // flat single-host topology (hierarchical paths stay off)
  {
    Topology t;
    t.rank = rank;
    t.size = size;
    t.local_size = static_cast<int>(EnvInt("HOROVOD_LOCAL_SIZE", size));
    t.local_rank = static_cast<int>(
        EnvInt("HOROVOD_LOCAL_RANK", rank % (t.local_size > 0
                                                 ? t.local_size : 1)));
    t.cross_size = static_cast<int>(
        EnvInt("HOROVOD_CROSS_SIZE",
               t.local_size > 0 ? size / t.local_size : 1));
    t.cross_rank = static_cast<int>(
        EnvInt("HOROVOD_CROSS_RANK",
               t.local_size > 0 ? rank / t.local_size : 0));
    ng->topo = t;
  }
  int64_t cache_capacity = EnvInt("HOROVOD_CACHE_CAPACITY", 1024);
  ng->cache = ResponseCache(static_cast<size_t>(cache_capacity));
  ng->cache_enabled = cache_capacity > 0;
  ng->stall = StallInspector(
      EnvDouble("HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0),
      EnvDouble("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0));

  if (size > 1) {
    ng->mesh = std::make_unique<PeerMesh>(rank, size);
    Status s = ng->mesh->Start();
    if (!s.ok()) {
      HVD_LOG(ERROR) << "peer mesh start failed: " << s.reason();
      ng->last_error = s.reason();
      g = ng;
      return 1;
    }
    ng->control = std::make_unique<ControlPlane>(
        rank, size, coord_host ? coord_host : "127.0.0.1", coord_port);
    std::vector<PeerInfo> roster;
    TopoClaim claim;
    claim.local_rank = ng->topo.local_rank;
    claim.local_size = ng->topo.local_size;
    claim.cross_rank = ng->topo.cross_rank;
    claim.cross_size = ng->topo.cross_size;
    if (EnvBool("HOROVOD_HIERARCHICAL_ALLREDUCE", false))
      claim.want_gates |= 0x1;
    if (EnvBool("HOROVOD_HIERARCHICAL_ALLGATHER", false))
      claim.want_gates |= 0x2;
    uint8_t agreed = 0;
    s = ng->control->Initialize(
        advertise_host ? advertise_host : "127.0.0.1", ng->mesh->port(),
        claim, roster, agreed);
    if (!s.ok()) {
      HVD_LOG(ERROR) << "control-plane handshake failed: " << s.reason();
      ng->last_error = s.reason();
      g = ng;
      return 1;
    }
    ng->mesh->SetRoster(std::move(roster));
    ng->hier_capable = (agreed & kTopoCapable) != 0;
    ng->hierarchical_allreduce = (agreed & kTopoHierAllreduce) != 0;
    ng->hierarchical_allgather = (agreed & kTopoHierAllgather) != 0;
    if (ng->hierarchical_allreduce || ng->hierarchical_allgather) {
      HVD_LOG(INFO) << "hierarchical collectives agreed on: local "
                    << ng->topo.local_rank << "/" << ng->topo.local_size
                    << ", cross " << ng->topo.cross_rank << "/"
                    << ng->topo.cross_size;
    }
    HVD_LOG(INFO) << "control plane up (coordinator " << coord_host << ":"
                  << coord_port << ", mesh port " << ng->mesh->port()
                  << ")";
  }

  // coordinator-only, like the reference (operations.cc:388-395)
  std::string tl = EnvStr("HOROVOD_TIMELINE", "");
  if (!tl.empty() && rank == 0) ng->timeline.Initialize(tl, rank);

  // autotuner runs on the coordinator; workers adopt tuned params from
  // the ResponseList (reference operations.cc:432-484 + controller.cc:33)
  {
    ParameterManager::Options po;
    po.enabled = EnvBool("HOROVOD_AUTOTUNE", false) && rank == 0;
    po.log_file = EnvStr("HOROVOD_AUTOTUNE_LOG", "");
    po.warmup_samples =
        static_cast<int>(EnvInt("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3));
    po.cycles_per_sample =
        static_cast<int>(EnvInt("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10));
    po.sample_repeats =
        static_cast<int>(EnvInt("HOROVOD_AUTOTUNE_SAMPLE_REPEATS", 2));
    po.max_samples = static_cast<int>(
        EnvInt("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20));
    po.gp_noise =
        EnvDouble("HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8);
    // categorical dims (reference parameter_manager.h:186-220): only
    // searchable when the deployment can exercise them
    po.tune_hierarchical = ng->hier_capable;
    po.tune_cache = ng->cache_enabled;
    ng->pm.Initialize(po, ng->fusion_threshold, ng->cycle_time_ms,
                      ng->hierarchical_allreduce, ng->cache_enabled);
    ng->last_cycle_tp = std::chrono::steady_clock::now();
  }

  g = ng;
  g->initialized.store(true);
  g->loop_thread = std::thread(BackgroundLoop);
  HVD_LOG(DEBUG) << "background loop started (cycle "
                 << ng->cycle_time_ms << " ms, fusion "
                 << ng->fusion_threshold << " bytes)";
  return 0;
}

int hvdc_shutdown() {
  if (g == nullptr || !g->initialized.load()) return 0;
  HVD_LOG(INFO) << "shutting down host core";
  g->shutdown_requested.store(true);
  if (g->loop_thread.joinable()) g->loop_thread.join();
  g->timeline.Shutdown();
  if (g->mesh) g->mesh->Shutdown();
  g->initialized.store(false);
  return 0;
}

int hvdc_is_initialized() {
  return (g != nullptr && g->initialized.load()) ? 1 : 0;
}

int hvdc_rank() { return g ? g->rank : -1; }
int hvdc_size() { return g ? g->size : -1; }

static int EnqueueImpl(int type, const char* name, const void* data,
                       const int64_t* shape, int ndim, int dtype, int op,
                       int root_rank, double prescale, double postscale,
                       bool borrow) {
  if (g == nullptr || !g->initialized.load()) {
    if (g) g->last_error = "horovod_tpu core is not initialized";
    return -1;
  }
  // reference parity (test_horovod_broadcast_rank_error): an
  // out-of-range root must error at enqueue, not hang the ring
  if (type == static_cast<int>(Request::BROADCAST) &&
      (root_rank < 0 || root_rank >= g->size)) {
    g->last_error = "broadcast root rank " + std::to_string(root_rank) +
                    " is outside [0, " + std::to_string(g->size) + ")";
    return -1;
  }
  TensorTableEntry e;
  e.name = name;
  e.type = static_cast<Request::Type>(type);
  e.dtype = static_cast<DataType>(dtype);
  for (int i = 0; i < ndim; ++i) e.shape.AddDim(shape[i]);
  e.root_rank = root_rank;
  e.op = static_cast<ReduceOp>(op);
  e.prescale = prescale;
  e.postscale = postscale;
  size_t nbytes = e.shape.num_elements() * DataTypeSize(e.dtype);
  if (borrow && data != nullptr) {
    // zero-copy: ops read — and for allreduce/adasum/broadcast write —
    // the caller's buffer directly; the caller keeps it alive until the
    // handle completes (the reference's framework-tensor wrap,
    // common.h:188-223)
    e.ext = static_cast<uint8_t*>(const_cast<void*>(data));
  } else {
    e.data.resize(nbytes);
    if (data != nullptr) {
      std::memcpy(e.data.data(), data, nbytes);
      g->copied_bytes.fetch_add(static_cast<int64_t>(nbytes));
    }
  }
  e.handle = g->handles.Allocate();
  int handle = e.handle;

  Request q;
  q.type = (e.op == ReduceOp::ADASUM && e.type == Request::ALLREDUCE)
               ? Request::ADASUM : e.type;
  q.request_rank = g->rank;
  q.dtype = e.dtype;
  q.tensor_name = e.name;
  q.root_rank = e.root_rank;
  q.shape = e.shape;
  q.prescale_factor = prescale;
  q.postscale_factor = postscale;
  q.reduce_op = static_cast<uint8_t>(op);

  Status s = g->queue.Add(std::move(e), q);
  if (!s.ok()) {
    g->handles.MarkDone(handle, s);
  }
  return handle;
}

int hvdc_enqueue(int type, const char* name, const void* data,
                 const int64_t* shape, int ndim, int dtype, int op,
                 int root_rank, double prescale, double postscale) {
  return EnqueueImpl(type, name, data, shape, ndim, dtype, op, root_rank,
                     prescale, postscale, /*borrow=*/false);
}

int hvdc_enqueue_borrow(int type, const char* name, void* data,
                        const int64_t* shape, int ndim, int dtype, int op,
                        int root_rank, double prescale, double postscale) {
  return EnqueueImpl(type, name, data, shape, ndim, dtype, op, root_rank,
                     prescale, postscale, /*borrow=*/true);
}

int64_t hvdc_copy_bytes() {
  return (g != nullptr) ? g->copied_bytes.load() : 0;
}

int hvdc_enqueue_join() {
  if (g == nullptr || !g->initialized.load()) return -1;
  std::lock_guard<std::mutex> lock(g->join_mu);
  if (g->join_handle >= 0) return g->join_handle;
  g->join_handle = g->handles.Allocate();
  g->self_joined = true;
  return g->join_handle;
}

int hvdc_poll(int handle) { return g ? g->handles.Poll(handle) : -2; }
int hvdc_wait(int handle) { return g ? g->handles.Wait(handle) : -2; }

const char* hvdc_error_message(int handle) {
  static thread_local std::string msg;
  msg = g ? g->handles.ErrorMessage(handle) : "core not initialized";
  return msg.c_str();
}

const char* hvdc_last_error() {
  static thread_local std::string msg;
  msg = g ? g->last_error : "core not initialized";
  return msg.c_str();
}

int64_t hvdc_output_size(int handle) {
  return g ? g->handles.OutputSize(handle) : -1;
}

int hvdc_copy_output(int handle, void* dst) {
  if (g == nullptr) return 1;
  int64_t n = g->handles.OutputSize(handle);
  if (!g->handles.CopyOutput(handle, dst)) return 1;
  if (n > 0) g->copied_bytes.fetch_add(n);
  return 0;
}

void hvdc_release(int handle) {
  if (g) g->handles.Release(handle);
}

int hvdc_control_bytes(int64_t* sent, int64_t* recvd) {
  if (g == nullptr || !g->initialized.load()) return -1;
  if (g->control == nullptr) {  // single process: no control plane
    if (sent) *sent = 0;
    if (recvd) *recvd = 0;
    return 0;
  }
  if (sent) *sent = g->control->round_bytes_sent();
  if (recvd) *recvd = g->control->round_bytes_recv();
  return 0;
}

int hvdc_data_bytes(int64_t* local_bytes, int64_t* cross_bytes) {
  if (g == nullptr || !g->initialized.load()) return -1;
  int64_t local = 0, cross = 0;
  if (g->mesh) {
    int my_host = g->topo.HostOf(g->rank);
    for (int p = 0; p < g->size; ++p) {
      if (p == g->rank) continue;
      int64_t b = g->mesh->bytes_sent_to(p);
      if (g->topo.HostOf(p) == my_host) local += b; else cross += b;
    }
  }
  if (local_bytes) *local_bytes = local;
  if (cross_bytes) *cross_bytes = cross;
  return 0;
}

int hvdc_autotune_state(int64_t* fusion_threshold, double* cycle_time_ms,
                        int* samples, int* done, int* hierarchical,
                        int* cache_enabled) {
  if (g == nullptr || !g->initialized.load()) return -1;
  std::lock_guard<std::mutex> lock(g->tune_mu);
  if (fusion_threshold) *fusion_threshold = g->fusion_threshold;
  if (cycle_time_ms) *cycle_time_ms = g->cycle_time_ms;
  if (hierarchical) *hierarchical = g->hierarchical_allreduce ? 1 : 0;
  if (cache_enabled) *cache_enabled = g->cache_enabled ? 1 : 0;
  // sample/convergence progress is coordinator-side knowledge; workers
  // report -1 samples and infer convergence from the adopted values
  bool coord = g->pm.enabled();
  if (samples) *samples = coord ? g->pm.samples() : -1;
  if (done) *done = coord ? (g->pm.done() ? 1 : 0) : 0;
  return EnvBool("HOROVOD_AUTOTUNE", false) ? 1 : 0;
}

int hvdc_barrier() {
  if (g == nullptr || !g->initialized.load()) return 1;
  static std::atomic<int> counter{0};
  std::string name = "barrier." + std::to_string(counter.fetch_add(1));
  int64_t shape = 1;
  uint8_t one = 1;
  int h = hvdc_enqueue(Request::BARRIER, name.c_str(), &one, &shape, 1,
                       static_cast<int>(DataType::UINT8),
                       static_cast<int>(ReduceOp::MAX), -1, 1.0, 1.0);
  if (h < 0) return 1;
  int rv = hvdc_wait(h);
  hvdc_release(h);
  return rv == 1 ? 0 : 1;
}
