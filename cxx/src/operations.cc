#include "hvd/operations.h"

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <unordered_set>

#include "hvd/controller.h"
#include "hvd/cpu_ops.h"
#include "hvd/negotiator.h"
#include "hvd/parameter_manager.h"
#include "hvd/peer_mesh.h"
#include "hvd/response_cache.h"
#include "hvd/stall_inspector.h"
#include "hvd/tensor_queue.h"
#include "hvd/timeline.h"

namespace hvd {
namespace {

// ---- handle manager (reference: torch/handle_manager.{h,cc}) -----------

struct HandleState {
  bool done = false;
  Status status;
  std::vector<uint8_t> output;
};

class HandleManager {
 public:
  int Allocate() {
    std::lock_guard<std::mutex> lock(mu_);
    int h = next_++;
    states_[h];
    return h;
  }
  void MarkDone(int h, Status s, std::vector<uint8_t> output = {}) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      auto it = states_.find(h);
      if (it == states_.end()) return;
      it->second.done = true;
      it->second.status = std::move(s);
      it->second.output = std::move(output);
    }
    cv_.notify_all();
  }
  // 0 pending, 1 ok, -1 error, -2 unknown handle
  int Poll(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end()) return -2;
    if (!it->second.done) return 0;
    return it->second.status.ok() ? 1 : -1;
  }
  int Wait(int h) {
    std::unique_lock<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end()) return -2;
    cv_.wait(lock, [&] { return states_.at(h).done; });
    return states_.at(h).status.ok() ? 1 : -1;
  }
  std::string ErrorMessage(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    return it == states_.end() ? "unknown handle" : it->second.status.reason();
  }
  int64_t OutputSize(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end() || !it->second.done) return -1;
    return static_cast<int64_t>(it->second.output.size());
  }
  bool CopyOutput(int h, void* dst) {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(h);
    if (it == states_.end() || !it->second.done) return false;
    std::memcpy(dst, it->second.output.data(), it->second.output.size());
    return true;
  }
  void Release(int h) {
    std::lock_guard<std::mutex> lock(mu_);
    states_.erase(h);
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int, HandleState> states_;
  int next_ = 0;
};

// ---- global state (reference: common/global_state.h) -------------------

struct Global {
  int rank = 0;
  int size = 1;
  std::unique_ptr<ControlPlane> control;
  std::unique_ptr<PeerMesh> mesh;
  TensorQueue queue;
  HandleManager handles;
  Negotiator negotiator{1};
  ResponseCache cache;
  StallInspector stall;
  Timeline timeline;

  std::thread loop_thread;
  std::atomic<bool> shutdown_requested{false};
  std::atomic<bool> initialized{false};
  double cycle_time_ms = 1.0;
  int64_t fusion_threshold = 64 * 1024 * 1024;

  // autotuner (coordinator scores cycles + proposes; tuned params ride
  // the ResponseList to workers — reference SynchronizeParameters).
  // tune_mu guards pm + the tuned fusion_threshold/cycle_time_ms pair
  // against user-thread introspection racing the loop thread.
  ParameterManager pm;
  std::mutex tune_mu;
  std::chrono::steady_clock::time_point last_cycle_tp;

  // join state
  std::vector<bool> joined_ranks;     // coordinator
  bool self_joined = false;
  int join_handle = -1;
  std::mutex join_mu;

  std::string last_error;
};

Global* g = nullptr;

int JoinedCount() {
  int c = 0;
  for (bool b : g->joined_ranks)
    if (b) c += 1;
  return c;
}

// ---- execution (reference: PerformOperation, operations.cc:227-304) ----

void CompleteEntry(TensorTableEntry& e, const Status& s) {
  if (e.handle >= 0)
    g->handles.MarkDone(e.handle, s, std::move(e.data));
}

void ExecuteFusedAllreduce(const Response& resp) {
  size_t esz = DataTypeSize(resp.dtype);
  int64_t total = 0;
  for (int64_t c : resp.tensor_sizes) total += c;

  std::vector<TensorTableEntry> entries(resp.tensor_names.size());
  std::vector<bool> have(resp.tensor_names.size(), false);
  for (size_t i = 0; i < resp.tensor_names.size(); ++i)
    have[i] = g->queue.Take(resp.tensor_names[i], entries[i]);

  // fusion buffer (reference FusionBufferManager + MemcpyInFusionBuffer) —
  // joined ranks contribute zeros (reference tensor_queue.h:39-41)
  std::vector<uint8_t> fused(total * esz, 0);
  int64_t off = 0;
  for (size_t i = 0; i < entries.size(); ++i) {
    int64_t nbytes = resp.tensor_sizes[i] * esz;
    if (have[i]) {
      if (entries[i].prescale != 1.0)
        ScaleInPlace(entries[i].data.data(), resp.tensor_sizes[i],
                     resp.dtype, entries[i].prescale);
      std::memcpy(fused.data() + off, entries[i].data.data(), nbytes);
    }
    off += nbytes;
  }

  ReduceOp op = static_cast<ReduceOp>(resp.reduce_op);

  Status st;
  g->timeline.ActivityStart(resp.tensor_names[0],
                            resp.type == Response::ADASUM
                                ? "ADASUM_ALLREDUCE" : "RING_ALLREDUCE");
  if (resp.type == Response::ADASUM) {
    st = AdasumAllreduce(*g->mesh, *g->control, g->rank, g->size,
                         fused.data(), total, resp.dtype);
  } else {
    // AVERAGE divides by the number of *contributing* (non-joined) ranks
    ReduceOp wire_op = (op == ReduceOp::AVERAGE) ? ReduceOp::SUM : op;
    st = RingAllreduce(*g->mesh, g->rank, g->size, fused.data(), total,
                       resp.dtype, wire_op);
    if (st.ok() && op == ReduceOp::AVERAGE) {
      int active = resp.active_ranks > 0 ? resp.active_ranks : g->size;
      ScaleInPlace(fused.data(), total, resp.dtype, 1.0 / active);
    }
  }
  g->timeline.ActivityEnd(resp.tensor_names[0]);

  off = 0;
  for (size_t i = 0; i < entries.size(); ++i) {
    int64_t nbytes = resp.tensor_sizes[i] * esz;
    if (have[i]) {
      std::memcpy(entries[i].data.data(), fused.data() + off, nbytes);
      if (st.ok() && entries[i].postscale != 1.0)
        ScaleInPlace(entries[i].data.data(), resp.tensor_sizes[i],
                     resp.dtype, entries[i].postscale);
      CompleteEntry(entries[i], st);
    }
    off += nbytes;
  }
}

void ExecuteAllgather(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;  // joined: no-op
  size_t esz = DataTypeSize(resp.dtype);
  int64_t row = 1;
  for (int d = 1; d < e.shape.ndim(); ++d) row *= e.shape.dim(d);
  std::vector<int64_t> counts;
  int64_t total = 0;
  for (int64_t dim0 : resp.tensor_sizes) {
    counts.push_back(dim0 * row);
    total += dim0 * row;
  }
  std::vector<uint8_t> out(total * esz);
  g->timeline.ActivityStart(e.name, "RING_ALLGATHER");
  Status st = RingAllgatherv(*g->mesh, g->rank, g->size, e.data.data(),
                             counts, resp.dtype, out.data());
  g->timeline.ActivityEnd(e.name);
  e.data = std::move(out);
  CompleteEntry(e, st);
}

void ExecuteBroadcast(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;
  g->timeline.ActivityStart(e.name, "BROADCAST");
  Status st = Broadcast(*g->mesh, g->rank, g->size, e.data.data(),
                        resp.tensor_sizes[0], resp.dtype, e.root_rank);
  g->timeline.ActivityEnd(e.name);
  CompleteEntry(e, st);
}

void ExecuteAlltoall(const Response& resp) {
  TensorTableEntry e;
  if (!g->queue.Take(resp.tensor_names[0], e)) return;
  int64_t count = resp.tensor_sizes[0];
  Status st;
  if (count % g->size != 0) {
    st = Status::InvalidArgument(
        "alltoall requires first dim divisible by size");
    CompleteEntry(e, st);
    return;
  }
  std::vector<uint8_t> out(e.data.size());
  g->timeline.ActivityStart(e.name, "ALLTOALL");
  st = AllToAll(*g->mesh, g->rank, g->size, e.data.data(), count / g->size,
                resp.dtype, out.data());
  g->timeline.ActivityEnd(e.name);
  e.data = std::move(out);
  CompleteEntry(e, st);
}

void ExecuteBarrier(const Response& resp) {
  TensorTableEntry e;
  bool have = g->queue.Take(resp.tensor_names[0], e);
  uint8_t one = 1;
  Status st = RingAllreduce(*g->mesh, g->rank, g->size, &one, 1,
                            DataType::UINT8, ReduceOp::MAX);
  if (have) CompleteEntry(e, st);
}

void ExecuteError(const Response& resp) {
  for (const auto& name : resp.tensor_names) {
    TensorTableEntry e;
    if (g->queue.Take(name, e))
      CompleteEntry(e, Status::InvalidArgument(resp.error_message));
  }
}

void ExecuteResponse(const Response& resp) {
  switch (resp.type) {
    case Response::ALLREDUCE:
    case Response::ADASUM:
      ExecuteFusedAllreduce(resp);
      break;
    case Response::ALLGATHER:
      ExecuteAllgather(resp);
      break;
    case Response::BROADCAST:
      ExecuteBroadcast(resp);
      break;
    case Response::ALLTOALL:
      ExecuteAlltoall(resp);
      break;
    case Response::REDUCESCATTER:
      // host path executes as allreduce; callers slice (XLA path has the
      // real reduce-scatter)
      ExecuteFusedAllreduce(resp);
      break;
    case Response::BARRIER:
      ExecuteBarrier(resp);
      break;
    case Response::JOIN: {
      std::lock_guard<std::mutex> lock(g->join_mu);
      if (g->join_handle >= 0) {
        g->handles.MarkDone(g->join_handle, Status::OK());
        g->join_handle = -1;
      }
      g->self_joined = false;
      std::fill(g->joined_ranks.begin(), g->joined_ranks.end(), false);
      break;
    }
    case Response::ERROR:
      ExecuteError(resp);
      break;
  }
}

// ---- negotiation cycle (reference: RunLoopOnce + ComputeResponseList) --

ResponseList CoordinatorNegotiate(std::vector<RequestList>& per_rank) {
  ResponseList rl;
  bool any_shutdown = false;
  bool join_changed = false;
  std::vector<std::string> ready;
  std::unordered_set<std::string> seen;

  for (int r = 0; r < g->size; ++r) {
    if (per_rank[r].shutdown) any_shutdown = true;
    std::vector<Request> normal;
    for (auto& q : per_rank[r].requests) {
      if (q.type == Request::JOIN) {
        if (!g->joined_ranks[r]) {
          g->joined_ranks[r] = true;
          join_changed = true;
        }
      } else {
        normal.push_back(std::move(q));
      }
    }
    for (const auto& name :
         g->negotiator.AddRequests(normal, JoinedCount()))
      if (seen.insert(name).second) ready.push_back(name);
  }
  if (join_changed) {
    for (const auto& name : g->negotiator.ReadyAfterJoin(JoinedCount()))
      if (seen.insert(name).second) ready.push_back(name);
  }

  int active = g->size - JoinedCount();
  for (const auto& name : ready) {
    g->timeline.NegotiateEnd(name);
    Response r;
    // steady-state fast path: identical-parameter repeats reuse the cached
    // validated response (reference response_cache.h:45-102; the
    // bitvector short-circuit of the full protocol maps onto our
    // synchronous rounds as a validation skip). A HIT requires EVERY
    // rank's request to match the cached params — checking one rank would
    // skip the cross-rank agreement guarantee.
    const std::vector<Request>* reqs = g->negotiator.Requests(name);
    bool all_hit = reqs != nullptr && !reqs->empty();
    if (all_hit)
      for (const Request& q : *reqs)
        if (g->cache.Cached(q) != ResponseCache::CacheState::HIT) {
          all_hit = false;
          break;
        }
    if (all_hit) {
      r = g->cache.Get(name);
      g->negotiator.Drop(name);
    } else {
      Request params =
          (reqs && !reqs->empty()) ? (*reqs)[0] : Request{};
      g->cache.Erase(name);  // params changed (or never cached)
      r = g->negotiator.BuildResponse(name);
      // allgather responses embed per-rank dims that may change step to
      // step; never cache them
      if (r.type != Response::ERROR && r.type != Response::ALLGATHER)
        g->cache.Put(params, r);
    }
    r.active_ranks = active;
    // allgather/broadcast/alltoall cannot zero-fill for joined ranks
    // (reference restriction, controller.cc:443-447,523-527)
    if (active < g->size &&
        (r.type == Response::ALLGATHER || r.type == Response::BROADCAST ||
         r.type == Response::ALLTOALL)) {
      r.error_message = "tensor " + r.tensor_names[0] +
                        ": allgather/broadcast/alltoall are not supported "
                        "after a rank has joined";
      r.type = Response::ERROR;
    }
    rl.responses.push_back(std::move(r));
  }
  rl.responses = Negotiator::Fuse(std::move(rl.responses),
                                  g->fusion_threshold);

  // all ranks joined -> emit JOIN response (reference controller.cc:290)
  if (g->size > 0 && JoinedCount() == g->size)
    rl.responses.push_back([] {
      Response r;
      r.type = Response::JOIN;
      r.tensor_names = {"join.noname"};
      return r;
    }());

  if (g->stall.Check(g->negotiator.Pending(), g->size)) any_shutdown = true;
  rl.shutdown = any_shutdown;

  // While tuning (and after convergence), every cycle's ResponseList
  // carries the coordinator's current proposal so all ranks run the
  // same (fusion threshold, cycle time).
  if (g->pm.enabled()) {
    std::lock_guard<std::mutex> lock(g->tune_mu);
    rl.has_tuned_params = true;
    rl.tuned_fusion_threshold = g->pm.fusion_threshold();
    rl.tuned_cycle_time_ms = g->pm.cycle_time_ms();
    g->fusion_threshold = g->pm.fusion_threshold();
    g->cycle_time_ms = g->pm.cycle_time_ms();
  }
  return rl;
}

// Payload bytes a ResponseList moves through the data plane (the
// autotuner's score numerator, reference parameter_manager score =
// bytes/sec over sample windows).
int64_t ResponsePayloadBytes(const ResponseList& rl) {
  int64_t bytes = 0;
  for (const auto& r : rl.responses) {
    if (r.type != Response::ALLREDUCE && r.type != Response::ADASUM &&
        r.type != Response::REDUCESCATTER)
      continue;
    int64_t elems = 0;
    for (int64_t c : r.tensor_sizes) elems += c;
    bytes += elems * static_cast<int64_t>(DataTypeSize(r.dtype));
  }
  return bytes;
}

bool RunLoopOnce() {
  RequestList mine;
  mine.requests = g->queue.PopRequests();
  {
    std::lock_guard<std::mutex> lock(g->join_mu);
    if (g->self_joined) {
      Request jq;
      jq.type = Request::JOIN;
      jq.request_rank = g->rank;
      mine.requests.push_back(jq);
      g->self_joined = false;  // announce once
    }
  }
  mine.shutdown = g->shutdown_requested.load();
  for (const auto& q : mine.requests)
    if (q.type != Request::JOIN)
      g->timeline.NegotiateStart(q.tensor_name, RequestTypeName(q.type));

  ResponseList rl;
  if (g->size == 1) {
    std::vector<RequestList> per_rank{mine};
    rl = CoordinatorNegotiate(per_rank);
  } else if (g->control->is_coordinator()) {
    std::vector<RequestList> per_rank;
    Status s = g->control->RecvReadyTensors(per_rank);
    if (!s.ok()) return false;
    per_rank[0] = std::move(mine);
    rl = CoordinatorNegotiate(per_rank);
    s = g->control->SendFinalTensors(rl);
    if (!s.ok()) return false;
  } else {
    Status s = g->control->SendReadyTensors(mine);
    if (!s.ok()) return false;
    s = g->control->RecvFinalTensors(rl);
    if (!s.ok()) return false;
    if (rl.has_tuned_params) {  // adopt the coordinator's tuned values
      std::lock_guard<std::mutex> lock(g->tune_mu);
      g->fusion_threshold = rl.tuned_fusion_threshold;
      g->cycle_time_ms = rl.tuned_cycle_time_ms;
    }
  }

  for (const auto& resp : rl.responses) {
    g->timeline.Start(resp.tensor_names[0],
                      std::string("OP_") + std::to_string(resp.type));
    ExecuteResponse(resp);
    g->timeline.End(resp.tensor_names[0]);
  }
  g->timeline.MarkCycle();

  // Coordinator scores the cycle (bytes moved / wall time incl. the
  // previous sleep) and advances the Bayesian-opt proposal loop. Idle
  // cycles are not scored — a pause between bursts of work must not
  // poison the throughput estimate.
  if (g->pm.active()) {
    auto now = std::chrono::steady_clock::now();
    double elapsed =
        std::chrono::duration<double>(now - g->last_cycle_tp).count();
    g->last_cycle_tp = now;
    int64_t bytes = ResponsePayloadBytes(rl);
    if (bytes > 0) {
      std::lock_guard<std::mutex> lock(g->tune_mu);
      g->pm.Update(bytes, elapsed);
    }
  }
  return !rl.shutdown;
}

void BackgroundLoop() {
  while (RunLoopOnce()) {
    std::this_thread::sleep_for(
        std::chrono::duration<double, std::milli>(g->cycle_time_ms));
  }
  // fail anything still pending (reference SHUT_DOWN_ERROR)
  for (auto& e : g->queue.DrainAll())
    CompleteEntry(e, Status::Aborted(
        "horovod_tpu core shut down before this op completed"));
  {
    std::lock_guard<std::mutex> lock(g->join_mu);
    if (g->join_handle >= 0) {
      g->handles.MarkDone(g->join_handle, Status::Aborted("shutdown"));
      g->join_handle = -1;
    }
  }
}

}  // namespace
}  // namespace hvd

// ---- C API -------------------------------------------------------------

using namespace hvd;

int hvdc_init(int rank, int size, const char* coord_host, int coord_port,
              const char* advertise_host) {
  if (g != nullptr && g->initialized.load()) return 0;
  if (g != nullptr) {  // re-init after shutdown
    delete g;
    g = nullptr;
  }
  auto* ng = new Global();
  ng->rank = rank;
  ng->size = size;
  ng->negotiator = Negotiator(size);
  ng->joined_ranks.assign(size, false);
  ng->cycle_time_ms = EnvDouble("HOROVOD_CYCLE_TIME", 1.0);
  ng->fusion_threshold =
      EnvInt("HOROVOD_FUSION_THRESHOLD", 64 * 1024 * 1024);
  ng->cache = ResponseCache(
      static_cast<size_t>(EnvInt("HOROVOD_CACHE_CAPACITY", 1024)));
  ng->stall = StallInspector(
      EnvDouble("HOROVOD_STALL_CHECK_TIME_SECONDS", 60.0),
      EnvDouble("HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", 0.0));

  if (size > 1) {
    ng->mesh = std::make_unique<PeerMesh>(rank, size);
    Status s = ng->mesh->Start();
    if (!s.ok()) {
      ng->last_error = s.reason();
      g = ng;
      return 1;
    }
    ng->control = std::make_unique<ControlPlane>(
        rank, size, coord_host ? coord_host : "127.0.0.1", coord_port);
    std::vector<PeerInfo> roster;
    s = ng->control->Initialize(
        advertise_host ? advertise_host : "127.0.0.1", ng->mesh->port(),
        roster);
    if (!s.ok()) {
      ng->last_error = s.reason();
      g = ng;
      return 1;
    }
    ng->mesh->SetRoster(std::move(roster));
  }

  // coordinator-only, like the reference (operations.cc:388-395)
  std::string tl = EnvStr("HOROVOD_TIMELINE", "");
  if (!tl.empty() && rank == 0) ng->timeline.Initialize(tl, rank);

  // autotuner runs on the coordinator; workers adopt tuned params from
  // the ResponseList (reference operations.cc:432-484 + controller.cc:33)
  {
    ParameterManager::Options po;
    po.enabled = EnvBool("HOROVOD_AUTOTUNE", false) && rank == 0;
    po.log_file = EnvStr("HOROVOD_AUTOTUNE_LOG", "");
    po.warmup_samples =
        static_cast<int>(EnvInt("HOROVOD_AUTOTUNE_WARMUP_SAMPLES", 3));
    po.cycles_per_sample =
        static_cast<int>(EnvInt("HOROVOD_AUTOTUNE_STEPS_PER_SAMPLE", 10));
    po.max_samples = static_cast<int>(
        EnvInt("HOROVOD_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", 20));
    po.gp_noise =
        EnvDouble("HOROVOD_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", 0.8);
    ng->pm.Initialize(po, ng->fusion_threshold, ng->cycle_time_ms);
    ng->last_cycle_tp = std::chrono::steady_clock::now();
  }

  g = ng;
  g->initialized.store(true);
  g->loop_thread = std::thread(BackgroundLoop);
  return 0;
}

int hvdc_shutdown() {
  if (g == nullptr || !g->initialized.load()) return 0;
  g->shutdown_requested.store(true);
  if (g->loop_thread.joinable()) g->loop_thread.join();
  g->timeline.Shutdown();
  if (g->mesh) g->mesh->Shutdown();
  g->initialized.store(false);
  return 0;
}

int hvdc_is_initialized() {
  return (g != nullptr && g->initialized.load()) ? 1 : 0;
}

int hvdc_rank() { return g ? g->rank : -1; }
int hvdc_size() { return g ? g->size : -1; }

int hvdc_enqueue(int type, const char* name, const void* data,
                 const int64_t* shape, int ndim, int dtype, int op,
                 int root_rank, double prescale, double postscale) {
  if (g == nullptr || !g->initialized.load()) {
    if (g) g->last_error = "horovod_tpu core is not initialized";
    return -1;
  }
  TensorTableEntry e;
  e.name = name;
  e.type = static_cast<Request::Type>(type);
  e.dtype = static_cast<DataType>(dtype);
  for (int i = 0; i < ndim; ++i) e.shape.AddDim(shape[i]);
  e.root_rank = root_rank;
  e.op = static_cast<ReduceOp>(op);
  e.prescale = prescale;
  e.postscale = postscale;
  size_t nbytes = e.shape.num_elements() * DataTypeSize(e.dtype);
  e.data.resize(nbytes);
  if (data != nullptr) std::memcpy(e.data.data(), data, nbytes);
  e.handle = g->handles.Allocate();
  int handle = e.handle;

  Request q;
  q.type = (e.op == ReduceOp::ADASUM && e.type == Request::ALLREDUCE)
               ? Request::ADASUM : e.type;
  q.request_rank = g->rank;
  q.dtype = e.dtype;
  q.tensor_name = e.name;
  q.root_rank = e.root_rank;
  q.shape = e.shape;
  q.prescale_factor = prescale;
  q.postscale_factor = postscale;
  q.reduce_op = static_cast<uint8_t>(op);

  Status s = g->queue.Add(std::move(e), q);
  if (!s.ok()) {
    g->handles.MarkDone(handle, s);
  }
  return handle;
}

int hvdc_enqueue_join() {
  if (g == nullptr || !g->initialized.load()) return -1;
  std::lock_guard<std::mutex> lock(g->join_mu);
  if (g->join_handle >= 0) return g->join_handle;
  g->join_handle = g->handles.Allocate();
  g->self_joined = true;
  return g->join_handle;
}

int hvdc_poll(int handle) { return g ? g->handles.Poll(handle) : -2; }
int hvdc_wait(int handle) { return g ? g->handles.Wait(handle) : -2; }

const char* hvdc_error_message(int handle) {
  static thread_local std::string msg;
  msg = g ? g->handles.ErrorMessage(handle) : "core not initialized";
  return msg.c_str();
}

const char* hvdc_last_error() {
  static thread_local std::string msg;
  msg = g ? g->last_error : "core not initialized";
  return msg.c_str();
}

int64_t hvdc_output_size(int handle) {
  return g ? g->handles.OutputSize(handle) : -1;
}

int hvdc_copy_output(int handle, void* dst) {
  return (g && g->handles.CopyOutput(handle, dst)) ? 0 : 1;
}

void hvdc_release(int handle) {
  if (g) g->handles.Release(handle);
}

int hvdc_autotune_state(int64_t* fusion_threshold, double* cycle_time_ms,
                        int* samples, int* done) {
  if (g == nullptr || !g->initialized.load()) return -1;
  std::lock_guard<std::mutex> lock(g->tune_mu);
  if (fusion_threshold) *fusion_threshold = g->fusion_threshold;
  if (cycle_time_ms) *cycle_time_ms = g->cycle_time_ms;
  // sample/convergence progress is coordinator-side knowledge; workers
  // report -1 samples and infer convergence from the adopted values
  bool coord = g->pm.enabled();
  if (samples) *samples = coord ? g->pm.samples() : -1;
  if (done) *done = coord ? (g->pm.done() ? 1 : 0) : 0;
  return EnvBool("HOROVOD_AUTOTUNE", false) ? 1 : 0;
}

int hvdc_barrier() {
  if (g == nullptr || !g->initialized.load()) return 1;
  static std::atomic<int> counter{0};
  std::string name = "barrier." + std::to_string(counter.fetch_add(1));
  int64_t shape = 1;
  uint8_t one = 1;
  int h = hvdc_enqueue(Request::BARRIER, name.c_str(), &one, &shape, 1,
                       static_cast<int>(DataType::UINT8),
                       static_cast<int>(ReduceOp::MAX), -1, 1.0, 1.0);
  if (h < 0) return 1;
  int rv = hvdc_wait(h);
  hvdc_release(h);
  return rv == 1 ? 0 : 1;
}
