#include "hvd/negotiator.h"

#include <algorithm>

namespace hvd {

std::vector<std::string> Negotiator::AddRequests(
    const std::vector<Request>& reqs, int joined_count) {
  std::vector<std::string> ready;
  for (const auto& q : reqs) {
    auto& slot = message_table_[q.tensor_name];
    if (slot.empty()) arrival_order_.push_back(q.tensor_name);
    slot.push_back(q);
    if (static_cast<int>(slot.size()) == size_ - joined_count)
      ready.push_back(q.tensor_name);
  }
  return ready;
}

std::vector<std::string> Negotiator::ReadyAfterJoin(int joined_count) {
  std::vector<std::string> ready;
  for (const auto& name : arrival_order_) {
    auto it = message_table_.find(name);
    if (it != message_table_.end() &&
        static_cast<int>(it->second.size()) >= size_ - joined_count)
      ready.push_back(name);
  }
  return ready;
}

Response Negotiator::BuildResponse(const std::string& name) {
  auto it = message_table_.find(name);
  Response resp;
  resp.tensor_names = {name};
  if (it == message_table_.end()) {
    resp.type = Response::ERROR;
    resp.error_message = "tensor " + name + " not in negotiation table";
    return resp;
  }
  std::vector<Request> reqs = std::move(it->second);
  Drop(name);

  const Request& first = reqs[0];
  resp.dtype = first.dtype;

  auto fail = [&](const std::string& msg) {
    resp.type = Response::ERROR;
    resp.error_message = "tensor " + name + ": " + msg;
    return resp;
  };

  // cross-rank agreement checks (reference ConstructResponse,
  // controller.cc:368-610)
  for (const auto& q : reqs) {
    if (q.type != first.type)
      return fail("mismatched collective types across ranks");
    if (q.dtype != first.dtype)
      return fail("mismatched dtypes across ranks");
    if (q.reduce_op != first.reduce_op)
      return fail("mismatched reduction ops across ranks");
  }
  resp.reduce_op = first.reduce_op;
  switch (first.type) {
    case Request::ALLREDUCE:
    case Request::ADASUM:
    case Request::ALLTOALL:
    case Request::REDUCESCATTER:
      for (const auto& q : reqs)
        if (q.shape != first.shape)
          return fail("mismatched shapes across ranks (" +
                      first.shape.DebugString() + " vs " +
                      q.shape.DebugString() + ")");
      resp.type = static_cast<Response::Type>(first.type);
      resp.tensor_sizes = {first.shape.num_elements()};
      break;
    case Request::BROADCAST: {
      for (const auto& q : reqs) {
        if (q.root_rank != first.root_rank)
          return fail("mismatched broadcast root ranks");
        if (q.shape != first.shape)
          return fail("mismatched shapes across ranks");
      }
      resp.type = Response::BROADCAST;
      resp.tensor_sizes = {first.shape.num_elements()};
      break;
    }
    case Request::ALLGATHER: {
      // shapes must agree on all dims but the first; record per-rank
      // first dims in rank order
      std::vector<int64_t> first_dims(reqs.size(), 0);
      for (const auto& q : reqs) {
        if (q.shape.ndim() != first.shape.ndim() || q.shape.ndim() == 0)
          return fail("allgather rank mismatch or zero-dim tensor");
        for (int d = 1; d < q.shape.ndim(); ++d)
          if (q.shape.dim(d) != first.shape.dim(d))
            return fail("allgather shapes differ beyond the first dim");
      }
      std::sort(reqs.begin(), reqs.end(),
                [](const Request& a, const Request& b) {
                  return a.request_rank < b.request_rank;
                });
      resp.tensor_sizes.clear();
      for (const auto& q : reqs) resp.tensor_sizes.push_back(q.shape.dim(0));
      resp.type = Response::ALLGATHER;
      break;
    }
    case Request::BARRIER:
      resp.type = Response::BARRIER;
      break;
    case Request::JOIN:
      resp.type = Response::JOIN;
      break;
  }
  return resp;
}

std::vector<Response> Negotiator::Fuse(std::vector<Response> responses,
                                       int64_t threshold_bytes) {
  std::vector<Response> out;
  std::vector<bool> used(responses.size(), false);
  for (size_t i = 0; i < responses.size(); ++i) {
    if (used[i]) continue;
    Response& r = responses[i];
    used[i] = true;
    bool fusable = (r.type == Response::ALLREDUCE ||
                    r.type == Response::ADASUM) &&
                   r.error_message.empty();
    if (!fusable) {
      out.push_back(std::move(r));
      continue;
    }
    int64_t esz = static_cast<int64_t>(DataTypeSize(r.dtype));
    int64_t bytes = r.tensor_sizes[0] * esz;
    // look-ahead: pull in later compatible responses while room remains
    for (size_t j = i + 1; j < responses.size(); ++j) {
      if (used[j]) continue;
      const Response& c = responses[j];
      if (c.type != r.type || c.dtype != r.dtype ||
          c.reduce_op != r.reduce_op || !c.error_message.empty())
        continue;
      int64_t cbytes = c.tensor_sizes[0] * esz;
      if (bytes + cbytes > threshold_bytes) continue;
      r.tensor_names.push_back(c.tensor_names[0]);
      r.tensor_sizes.push_back(c.tensor_sizes[0]);
      bytes += cbytes;
      used[j] = true;
    }
    out.push_back(std::move(r));
  }
  return out;
}

const Request* Negotiator::FirstRequest(const std::string& name) const {
  auto it = message_table_.find(name);
  if (it == message_table_.end() || it->second.empty()) return nullptr;
  return &it->second[0];
}

const std::vector<Request>* Negotiator::Requests(
    const std::string& name) const {
  auto it = message_table_.find(name);
  if (it == message_table_.end()) return nullptr;
  return &it->second;
}

void Negotiator::Drop(const std::string& name) {
  message_table_.erase(name);
  arrival_order_.erase(
      std::remove(arrival_order_.begin(), arrival_order_.end(), name),
      arrival_order_.end());
}

std::vector<std::pair<std::string, std::vector<int>>> Negotiator::Pending()
    const {
  std::vector<std::pair<std::string, std::vector<int>>> out;
  for (const auto& name : arrival_order_) {
    auto it = message_table_.find(name);
    if (it == message_table_.end()) continue;
    std::vector<int> ranks;
    for (const auto& q : it->second) ranks.push_back(q.request_rank);
    out.emplace_back(name, std::move(ranks));
  }
  return out;
}

}  // namespace hvd
