#include "hvd/socket.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstring>
#include <thread>

namespace hvd {

namespace {

void SetNoDelay(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
}

}  // namespace

TcpConnection::~TcpConnection() {
  if (fd_ >= 0) ::close(fd_);
}

std::unique_ptr<TcpConnection> TcpConnection::Connect(const std::string& host,
                                                      int port,
                                                      double timeout_sec) {
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_sec);
  while (true) {
    struct addrinfo hints;
    std::memset(&hints, 0, sizeof(hints));
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    struct addrinfo* res = nullptr;
    std::string port_str = std::to_string(port);
    if (getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res) == 0) {
      for (struct addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
        int fd = ::socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
        if (fd < 0) continue;
        if (::connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) {
          freeaddrinfo(res);
          SetNoDelay(fd);
          return std::make_unique<TcpConnection>(fd);
        }
        ::close(fd);
      }
      freeaddrinfo(res);
    }
    if (std::chrono::steady_clock::now() > deadline) return nullptr;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

Status TcpConnection::SendFrame(const void* data, uint32_t len) {
  uint32_t hdr = len;
  Status s = SendRaw(&hdr, 4);
  if (!s.ok()) return s;
  return SendRaw(data, len);
}

Status TcpConnection::RecvFrame(std::vector<uint8_t>& out) {
  uint32_t len = 0;
  Status s = RecvRaw(&len, 4);
  if (!s.ok()) return s;
  out.resize(len);
  if (len == 0) return Status::OK();
  return RecvRaw(out.data(), len);
}

namespace {

Status WaitReady(int fd, bool for_send) {
  struct pollfd pfd;
  pfd.fd = fd;
  pfd.events = for_send ? POLLOUT : POLLIN;
  int rv = ::poll(&pfd, 1, 120000);
  if (rv < 0)
    return Status::Unknown(std::string("poll failed: ") +
                           std::strerror(errno));
  if (rv == 0) return Status::Unknown("socket IO timed out");
  return Status::OK();
}

}  // namespace

void TcpConnection::SetNonBlocking() {
  int flags = fcntl(fd_, F_GETFL, 0);
  fcntl(fd_, F_SETFL, flags | O_NONBLOCK);
}

Status TcpConnection::SendRaw(const void* data, size_t len) {
  const uint8_t* p = static_cast<const uint8_t*>(data);
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::send(fd_, p + sent, len - sent, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        Status s = WaitReady(fd_, true);
        if (!s.ok()) return s;
        continue;
      }
      return Status::Unknown(std::string("send failed: ") +
                             std::strerror(errno));
    }
    sent += static_cast<size_t>(n);
  }
  return Status::OK();
}

Status TcpConnection::RecvFrameDeadline(std::vector<uint8_t>& out,
                                        double timeout_sec,
                                        uint32_t max_len) {
  // Whole-frame absolute deadline (header + payload): a peer dripping
  // bytes cannot keep resetting a per-recv timer. Temporarily
  // non-blocking; original flags restored on every exit path.
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_sec);
  int flags = fcntl(fd_, F_GETFL, 0);
  fcntl(fd_, F_SETFL, flags | O_NONBLOCK);
  auto recv_all = [&](void* data, size_t len) -> Status {
    uint8_t* p = static_cast<uint8_t*>(data);
    size_t got = 0;
    while (got < len) {
      ssize_t n = ::recv(fd_, p + got, len - got, 0);
      if (n > 0) {
        got += static_cast<size_t>(n);
        continue;
      }
      if (n == 0) return Status::Aborted("connection closed by peer");
      if (errno == EINTR) continue;
      if (errno != EAGAIN && errno != EWOULDBLOCK)
        return Status::Unknown(std::string("recv failed: ") +
                               std::strerror(errno));
      auto left = std::chrono::duration_cast<std::chrono::milliseconds>(
          deadline - std::chrono::steady_clock::now());
      if (left.count() <= 0) return Status::Unknown("recv deadline exceeded");
      struct pollfd pfd;
      pfd.fd = fd_;
      pfd.events = POLLIN;
      ::poll(&pfd, 1, static_cast<int>(left.count()));
    }
    return Status::OK();
  };
  uint32_t len = 0;
  Status s = recv_all(&len, 4);
  if (s.ok() && len > max_len)
    s = Status::InvalidArgument("frame length " + std::to_string(len) +
                                " exceeds handshake cap");
  if (s.ok()) {
    out.resize(len);
    if (len > 0) s = recv_all(out.data(), len);
  }
  fcntl(fd_, F_SETFL, flags);
  return s;
}

Status TcpConnection::RecvRaw(void* data, size_t len) {
  uint8_t* p = static_cast<uint8_t*>(data);
  size_t got = 0;
  while (got < len) {
    ssize_t n = ::recv(fd_, p + got, len - got, 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      if (errno == EAGAIN || errno == EWOULDBLOCK) {
        Status s = WaitReady(fd_, false);
        if (!s.ok()) return s;
        continue;
      }
      return Status::Unknown(std::string("recv failed: ") +
                             std::strerror(errno));
    }
    if (n == 0) return Status::Aborted("connection closed by peer");
    got += static_cast<size_t>(n);
  }
  return Status::OK();
}

TcpServer::TcpServer(int port) {
  fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd_ < 0) return;
  int one = 1;
  setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  std::memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd_, reinterpret_cast<struct sockaddr*>(&addr),
             sizeof(addr)) != 0 ||
      ::listen(fd_, 128) != 0) {
    ::close(fd_);
    fd_ = -1;
    return;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd_, reinterpret_cast<struct sockaddr*>(&addr), &alen);
  port_ = ntohs(addr.sin_port);
}

TcpServer::~TcpServer() {
  if (fd_ >= 0) ::close(fd_);
}

std::unique_ptr<TcpConnection> TcpServer::Accept(double timeout_sec) {
  struct pollfd pfd;
  pfd.fd = fd_;
  pfd.events = POLLIN;
  int rv = ::poll(&pfd, 1, static_cast<int>(timeout_sec * 1000));
  if (rv <= 0) return nullptr;
  int cfd = ::accept(fd_, nullptr, nullptr);
  if (cfd < 0) return nullptr;
  int one = 1;
  setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return std::make_unique<TcpConnection>(cfd);
}

}  // namespace hvd
