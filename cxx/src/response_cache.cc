#include "hvd/response_cache.h"

namespace hvd {

ResponseCache::CacheState ResponseCache::Cached(const Request& req) const {
  auto it = entries_.find(req.tensor_name);
  if (it == entries_.end()) return CacheState::MISS;
  const Request& p = it->second.params;
  if (p.type == req.type && p.dtype == req.dtype && p.shape == req.shape &&
      p.root_rank == req.root_rank && p.reduce_op == req.reduce_op &&
      p.prescale_factor == req.prescale_factor &&
      p.postscale_factor == req.postscale_factor)
    return CacheState::HIT;
  return CacheState::INVALID;
}

std::string ResponseCache::Put(const Request& req, const Response& resp) {
  auto it = entries_.find(req.tensor_name);
  if (it != entries_.end()) {
    it->second.response = resp;
    it->second.params = req;
    Touch(req.tensor_name);
    return {};
  }
  std::string evicted;
  if (entries_.size() >= capacity_) {
    // evict least-recently-used
    evicted = lru_.back();
    auto vit = entries_.find(evicted);
    free_bits_.push_back(vit->second.bit);
    bit_to_name_.erase(vit->second.bit);
    entries_.erase(vit);
    lru_.pop_back();
  }
  uint32_t bit;
  if (!free_bits_.empty()) {
    bit = free_bits_.back();
    free_bits_.pop_back();
  } else {
    bit = next_bit_++;
  }
  lru_.push_front(req.tensor_name);
  Entry e{resp, req, bit, lru_.begin()};
  entries_.emplace(req.tensor_name, std::move(e));
  bit_to_name_[bit] = req.tensor_name;
  return evicted;
}

const Response& ResponseCache::Get(const std::string& name) {
  Touch(name);
  return entries_.at(name).response;
}

uint32_t ResponseCache::GetBit(const std::string& name) const {
  return entries_.at(name).bit;
}

std::string ResponseCache::NameForBit(uint32_t bit) const {
  auto it = bit_to_name_.find(bit);
  return it == bit_to_name_.end() ? std::string() : it->second;
}

Response::Type ResponseCache::TypeForBit(uint32_t bit) const {
  auto it = bit_to_name_.find(bit);
  if (it == bit_to_name_.end()) return Response::ERROR;
  return entries_.at(it->second).response.type;
}

void ResponseCache::Erase(const std::string& name) {
  auto it = entries_.find(name);
  if (it == entries_.end()) return;
  free_bits_.push_back(it->second.bit);
  bit_to_name_.erase(it->second.bit);
  lru_.erase(it->second.lru_it);
  entries_.erase(it);
}

void ResponseCache::Touch(const std::string& name) {
  auto it = entries_.find(name);
  if (it == entries_.end()) return;
  lru_.erase(it->second.lru_it);
  lru_.push_front(name);
  it->second.lru_it = lru_.begin();
}

std::vector<Response> ResponseCache::ResponsesForBits(
    const std::vector<uint64_t>& bits) const {
  std::vector<Response> out;
  for (size_t w = 0; w < bits.size(); ++w) {
    uint64_t word = bits[w];
    while (word) {
      int b = __builtin_ctzll(word);
      word &= word - 1;
      uint32_t bit = static_cast<uint32_t>(w * 64 + b);
      auto it = bit_to_name_.find(bit);
      if (it == bit_to_name_.end()) continue;
      out.push_back(entries_.at(it->second).response);
    }
  }
  return out;
}

std::vector<uint64_t> ResponseCache::PackBits(
    const std::vector<std::string>& names) const {
  std::vector<uint64_t> bits(NumBitWords(), 0);
  for (const auto& n : names) {
    auto it = entries_.find(n);
    if (it == entries_.end()) continue;
    uint32_t b = it->second.bit;
    if (b / 64 < bits.size()) bits[b / 64] |= (uint64_t{1} << (b % 64));
  }
  return bits;
}

}  // namespace hvd
