#include "hvd/common.h"

#include <cstdlib>
#include <sstream>

namespace hvd {

const char* DataTypeName(DataType dt) {
  switch (dt) {
    case DataType::UINT8: return "uint8";
    case DataType::INT8: return "int8";
    case DataType::UINT16: return "uint16";
    case DataType::INT16: return "int16";
    case DataType::INT32: return "int32";
    case DataType::INT64: return "int64";
    case DataType::FLOAT16: return "float16";
    case DataType::FLOAT32: return "float32";
    case DataType::FLOAT64: return "float64";
    case DataType::BOOL: return "bool";
    case DataType::BFLOAT16: return "bfloat16";
  }
  return "unknown";
}

std::string TensorShape::DebugString() const {
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < dims_.size(); ++i) {
    if (i) os << ", ";
    os << dims_[i];
  }
  os << "]";
  return os.str();
}

int64_t EnvInt(const char* name, int64_t dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return std::strtoll(v, nullptr, 10);
}

double EnvDouble(const char* name, double dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return std::strtod(v, nullptr);
}

std::string EnvStr(const char* name, const std::string& dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return std::string(v);
}

bool EnvBool(const char* name, bool dflt) {
  const char* v = std::getenv(name);
  if (v == nullptr || *v == '\0') return dflt;
  return std::string(v) == "1" || std::string(v) == "true" ||
         std::string(v) == "True";
}

}  // namespace hvd
