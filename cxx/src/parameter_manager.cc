#include "hvd/parameter_manager.h"

#include <cmath>

namespace hvd {

namespace {

// param space bounds (reference tunes fusion 0..64MB, cycle 1..25ms)
constexpr double kMaxLogFusion = 26.0;  // 2^26 = 64 MB
constexpr double kMinLogFusion = 16.0;  // 64 KB
constexpr double kMaxCycleMs = 25.0;
constexpr double kMinCycleMs = 0.5;

std::vector<double> Encode(int64_t fusion, double cycle_ms) {
  double lf = std::log2(static_cast<double>(fusion < 1 ? 1 : fusion));
  return {(lf - kMinLogFusion) / (kMaxLogFusion - kMinLogFusion),
          (cycle_ms - kMinCycleMs) / (kMaxCycleMs - kMinCycleMs)};
}

void Decode(const std::vector<double>& x, int64_t& fusion,
            double& cycle_ms) {
  double lf = kMinLogFusion + x[0] * (kMaxLogFusion - kMinLogFusion);
  fusion = static_cast<int64_t>(std::pow(2.0, lf));
  cycle_ms = kMinCycleMs + x[1] * (kMaxCycleMs - kMinCycleMs);
}

}  // namespace

void ParameterManager::Initialize(const Options& opts,
                                  int64_t fusion_threshold,
                                  double cycle_time_ms) {
  opts_ = opts;
  gp_ = GaussianProcess(0.3, opts.gp_noise);
  current_fusion_ = best_fusion_ = fusion_threshold;
  current_cycle_ms_ = best_cycle_ms_ = cycle_time_ms;
  warmup_left_ = opts.warmup_samples;
  rng_state_ = opts.seed;
  if (!opts.log_file.empty() && opts.enabled) {
    log_.open(opts.log_file, std::ios::out | std::ios::trunc);
    log_ << "sample,fusion_threshold,cycle_time_ms,score_bytes_per_sec\n";
  }
}

double ParameterManager::NextRand() {
  // xorshift64
  rng_state_ ^= rng_state_ << 13;
  rng_state_ ^= rng_state_ >> 7;
  rng_state_ ^= rng_state_ << 17;
  return static_cast<double>(rng_state_ % 1000000) / 1000000.0;
}

bool ParameterManager::Update(int64_t bytes, double elapsed_sec) {
  if (!active()) return false;
  bytes_acc_ += bytes;
  time_acc_ += elapsed_sec;
  if (++cycles_ < opts_.cycles_per_sample) return false;

  double score = time_acc_ > 0
                     ? static_cast<double>(bytes_acc_) / time_acc_ : 0;
  cycles_ = 0;
  bytes_acc_ = 0;
  time_acc_ = 0;

  if (warmup_left_ > 0) {  // discard warmup windows (reference warmup)
    --warmup_left_;
    return false;
  }

  xs_.push_back(Encode(current_fusion_, current_cycle_ms_));
  ys_.push_back(score);
  if (log_.is_open()) {
    log_ << ys_.size() << "," << current_fusion_ << ","
         << current_cycle_ms_ << "," << score << "\n";
    log_.flush();
  }
  if (score > best_score_) {
    best_score_ = score;
    best_fusion_ = current_fusion_;
    best_cycle_ms_ = current_cycle_ms_;
  }
  if (static_cast<int>(ys_.size()) >= opts_.max_samples) {
    current_fusion_ = best_fusion_;
    current_cycle_ms_ = best_cycle_ms_;
    done_ = true;
    if (log_.is_open()) {
      log_ << "converged," << best_fusion_ << "," << best_cycle_ms_ << ","
           << best_score_ << "\n";
      log_.flush();
    }
    return true;
  }
  Propose();
  return true;
}

void ParameterManager::Propose() {
  // first few samples explore randomly, then EI over the GP posterior
  if (ys_.size() < 3) {
    std::vector<double> x = {NextRand(), NextRand()};
    Decode(x, current_fusion_, current_cycle_ms_);
    return;
  }
  gp_.Fit(xs_, ys_);
  double best_ei = -1;
  std::vector<double> best_x = xs_.back();
  for (int c = 0; c < 64; ++c) {
    std::vector<double> x = {NextRand(), NextRand()};
    double ei = gp_.ExpectedImprovement(x);
    if (ei > best_ei) {
      best_ei = ei;
      best_x = x;
    }
  }
  Decode(best_x, current_fusion_, current_cycle_ms_);
}

}  // namespace hvd
