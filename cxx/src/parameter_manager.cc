#include "hvd/parameter_manager.h"

#include <cmath>

namespace hvd {

namespace {

// param space bounds (reference tunes fusion 0..64MB, cycle 1..25ms)
constexpr double kMaxLogFusion = 26.0;  // 2^26 = 64 MB
constexpr double kMinLogFusion = 16.0;  // 64 KB
constexpr double kMaxCycleMs = 25.0;
constexpr double kMinCycleMs = 0.5;

std::vector<double> Encode(int64_t fusion, double cycle_ms, bool hier,
                           bool cache) {
  double lf = std::log2(static_cast<double>(fusion < 1 ? 1 : fusion));
  return {(lf - kMinLogFusion) / (kMaxLogFusion - kMinLogFusion),
          (cycle_ms - kMinCycleMs) / (kMaxCycleMs - kMinCycleMs),
          hier ? 1.0 : 0.0, cache ? 1.0 : 0.0};
}

void Decode(const std::vector<double>& x, int64_t& fusion, double& cycle_ms,
            bool& hier, bool& cache) {
  double lf = kMinLogFusion + x[0] * (kMaxLogFusion - kMinLogFusion);
  fusion = static_cast<int64_t>(std::pow(2.0, lf));
  cycle_ms = kMinCycleMs + x[1] * (kMaxCycleMs - kMinCycleMs);
  hier = x[2] >= 0.5;
  cache = x[3] >= 0.5;
}

}  // namespace

void ParameterManager::Initialize(const Options& opts,
                                  int64_t fusion_threshold,
                                  double cycle_time_ms, bool hierarchical,
                                  bool cache_enabled) {
  opts_ = opts;
  gp_ = GaussianProcess(0.3, opts.gp_noise);
  current_fusion_ = best_fusion_ = fusion_threshold;
  current_cycle_ms_ = best_cycle_ms_ = cycle_time_ms;
  current_hier_ = best_hier_ = hierarchical;
  current_cache_ = best_cache_ = cache_enabled;
  // the initial config occupies one cell of the 2x2 categorical grid;
  // the random-phase proposals walk the OTHER cells starting after it
  init_grid_ = (hierarchical ? 2u : 0u) | (cache_enabled ? 1u : 0u);
  warmup_left_ = opts.warmup_samples;
  rng_state_ = opts.seed;
  if (!opts.log_file.empty() && opts.enabled) {
    log_.open(opts.log_file, std::ios::out | std::ios::trunc);
    log_ << "sample,fusion_threshold,cycle_time_ms,hierarchical,cache,"
            "score_bytes_per_sec\n";
  }
}

double ParameterManager::NextRand() {
  // xorshift64
  rng_state_ ^= rng_state_ << 13;
  rng_state_ ^= rng_state_ >> 7;
  rng_state_ ^= rng_state_ << 17;
  return static_cast<double>(rng_state_ % 1000000) / 1000000.0;
}

bool ParameterManager::Update(int64_t bytes, double elapsed_sec) {
  if (!active()) return false;
  bytes_acc_ += bytes;
  time_acc_ += elapsed_sec;
  if (++cycles_ < opts_.cycles_per_sample) return false;

  double score = time_acc_ > 0
                     ? static_cast<double>(bytes_acc_) / time_acc_ : 0;
  cycles_ = 0;
  bytes_acc_ = 0;
  time_acc_ = 0;

  if (warmup_left_ > 0) {  // discard warmup windows (reference warmup)
    --warmup_left_;
    return false;
  }

  // average repeated windows at the SAME proposal before recording: one
  // window of whatever happened to be in flight is too noisy a sample
  // for the GP (the reference averages repeated samples the same way)
  window_scores_.push_back(score);
  if (static_cast<int>(window_scores_.size()) < opts_.sample_repeats)
    return false;
  score = 0;
  for (double w : window_scores_) score += w;
  score /= static_cast<double>(window_scores_.size());
  window_scores_.clear();

  xs_.push_back(Encode(current_fusion_, current_cycle_ms_, current_hier_,
                       current_cache_));
  ys_.push_back(score);
  if (log_.is_open()) {
    log_ << ys_.size() << "," << current_fusion_ << ","
         << current_cycle_ms_ << "," << (current_hier_ ? 1 : 0) << ","
         << (current_cache_ ? 1 : 0) << "," << score << "\n";
    log_.flush();
  }
  if (score > best_score_) {
    best_score_ = score;
    best_fusion_ = current_fusion_;
    best_cycle_ms_ = current_cycle_ms_;
    best_hier_ = current_hier_;
    best_cache_ = current_cache_;
  }
  if (static_cast<int>(ys_.size()) >= opts_.max_samples) {
    current_fusion_ = best_fusion_;
    current_cycle_ms_ = best_cycle_ms_;
    current_hier_ = best_hier_;
    current_cache_ = best_cache_;
    done_ = true;
    if (log_.is_open()) {
      log_ << "converged," << best_fusion_ << "," << best_cycle_ms_ << ","
           << (best_hier_ ? 1 : 0) << "," << (best_cache_ ? 1 : 0) << ","
           << best_score_ << "\n";
      log_.flush();
    }
    return true;
  }
  Propose();
  return true;
}

void ParameterManager::Propose() {
  // A candidate point: continuous dims uniform, categorical dims 0/1.
  // During the initial exploration phase the categoricals walk their
  // combination grid by sample index (00, 01, 10, 11, ...) so every
  // enabled category is guaranteed a trial regardless of RNG luck —
  // the BO-friendly analogue of the reference's grid-chunk walk.
  auto candidate = [&](size_t grid_idx) {
    grid_idx %= 4;
    std::vector<double> x = {NextRand(), NextRand(), 0.0, 0.0};
    if (opts_.tune_hierarchical) x[2] = (grid_idx >> 1) & 1 ? 1.0 : 0.0;
    else x[2] = current_hier_ ? 1.0 : 0.0;   // pinned
    if (opts_.tune_cache) x[3] = grid_idx & 1 ? 1.0 : 0.0;
    else x[3] = current_cache_ ? 1.0 : 0.0;  // pinned
    return x;
  };

  size_t n_random = 3;
  if (opts_.tune_hierarchical || opts_.tune_cache)
    n_random = 4;  // initial config + 3 proposals = the full 2x2 grid
  if (ys_.size() < n_random) {
    // Propose() runs AFTER sample k was recorded (ys_.size() = k >= 1);
    // offsetting by the initial config's own grid cell makes proposals
    // 1..3 cover exactly the three cells the initial config did not
    std::vector<double> x = candidate(init_grid_ + ys_.size());
    Decode(x, current_fusion_, current_cycle_ms_, current_hier_,
           current_cache_);
    return;
  }
  gp_.Fit(xs_, ys_);
  double best_ei = -1;
  std::vector<double> best_x = xs_.back();
  for (int c = 0; c < 64; ++c) {
    // EI phase: categorical coords drawn uniformly (candidate() with a
    // random grid index), continuous coords uniform
    std::vector<double> x =
        candidate(static_cast<size_t>(NextRand() * 4.0));
    double ei = gp_.ExpectedImprovement(x);
    if (ei > best_ei) {
      best_ei = ei;
      best_x = x;
    }
  }
  Decode(best_x, current_fusion_, current_cycle_ms_, current_hier_,
         current_cache_);
}

}  // namespace hvd
