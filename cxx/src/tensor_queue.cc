#include "hvd/tensor_queue.h"

namespace hvd {

Status TensorQueue::Add(TensorTableEntry entry, const Request& req) {
  std::lock_guard<std::mutex> lock(mu_);
  if (table_.count(entry.name)) {
    return Status::InvalidArgument(
        "Duplicate tensor name " + entry.name +
        "; a previous collective with this name is still pending");
  }
  pending_.push_back(req);
  table_.emplace(entry.name, std::move(entry));
  return Status::OK();
}

std::vector<Request> TensorQueue::PopRequests() {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<Request> out(pending_.begin(), pending_.end());
  pending_.clear();
  return out;
}

void TensorQueue::Requeue(const Request& req) {
  std::lock_guard<std::mutex> lock(mu_);
  pending_.push_front(req);
}

bool TensorQueue::Take(const std::string& name, TensorTableEntry& out) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = table_.find(name);
  if (it == table_.end()) return false;
  out = std::move(it->second);
  table_.erase(it);
  return true;
}

std::vector<std::string> TensorQueue::PendingNames() {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<std::string> names;
  names.reserve(table_.size());
  for (const auto& kv : table_) names.push_back(kv.first);
  return names;
}

std::vector<TensorTableEntry> TensorQueue::DrainAll() {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<TensorTableEntry> out;
  out.reserve(table_.size());
  for (auto& kv : table_) out.push_back(std::move(kv.second));
  table_.clear();
  pending_.clear();
  return out;
}

}  // namespace hvd
