#include "hvd/gaussian_process.h"

#include <cmath>

namespace hvd {

double GaussianProcess::Kernel(const std::vector<double>& a,
                               const std::vector<double>& b) const {
  double d2 = 0;
  for (size_t i = 0; i < a.size(); ++i) {
    double d = a[i] - b[i];
    d2 += d * d;
  }
  return std::exp(-d2 / (2.0 * length_scale_ * length_scale_));
}

void GaussianProcess::Fit(const std::vector<std::vector<double>>& xs,
                          const std::vector<double>& ys) {
  size_t n = xs.size();
  xs_ = xs;
  // z-score normalize targets
  y_mean_ = 0;
  for (double y : ys) y_mean_ += y;
  y_mean_ /= n;
  y_std_ = 0;
  for (double y : ys) y_std_ += (y - y_mean_) * (y - y_mean_);
  y_std_ = std::sqrt(y_std_ / n);
  if (y_std_ < 1e-12) y_std_ = 1.0;
  ys_norm_.resize(n);
  best_norm_ = -1e300;
  for (size_t i = 0; i < n; ++i) {
    ys_norm_[i] = (ys[i] - y_mean_) / y_std_;
    if (ys_norm_[i] > best_norm_) best_norm_ = ys_norm_[i];
  }

  // K + noise*I, Cholesky L L^T = K
  std::vector<std::vector<double>> K(n, std::vector<double>(n));
  for (size_t i = 0; i < n; ++i)
    for (size_t j = 0; j < n; ++j) {
      K[i][j] = Kernel(xs_[i], xs_[j]);
      if (i == j) K[i][j] += noise_;
    }
  chol_.assign(n, std::vector<double>(n, 0.0));
  for (size_t i = 0; i < n; ++i) {
    for (size_t j = 0; j <= i; ++j) {
      double sum = K[i][j];
      for (size_t k = 0; k < j; ++k) sum -= chol_[i][k] * chol_[j][k];
      if (i == j) {
        chol_[i][i] = std::sqrt(sum > 1e-12 ? sum : 1e-12);
      } else {
        chol_[i][j] = sum / chol_[j][j];
      }
    }
  }
  // alpha = K^-1 y via two triangular solves
  std::vector<double> z(n);
  for (size_t i = 0; i < n; ++i) {
    double sum = ys_norm_[i];
    for (size_t k = 0; k < i; ++k) sum -= chol_[i][k] * z[k];
    z[i] = sum / chol_[i][i];
  }
  alpha_.assign(n, 0.0);
  for (size_t ii = n; ii-- > 0;) {
    double sum = z[ii];
    for (size_t k = ii + 1; k < n; ++k) sum -= chol_[k][ii] * alpha_[k];
    alpha_[ii] = sum / chol_[ii][ii];
  }
}

void GaussianProcess::Predict(const std::vector<double>& x, double& mean,
                              double& var) const {
  size_t n = xs_.size();
  std::vector<double> kstar(n);
  for (size_t i = 0; i < n; ++i) kstar[i] = Kernel(x, xs_[i]);
  mean = 0;
  for (size_t i = 0; i < n; ++i) mean += kstar[i] * alpha_[i];
  // v = L^-1 k*, var = k(x,x) - v^T v
  std::vector<double> v(n);
  for (size_t i = 0; i < n; ++i) {
    double sum = kstar[i];
    for (size_t k = 0; k < i; ++k) sum -= chol_[i][k] * v[k];
    v[i] = sum / chol_[i][i];
  }
  var = Kernel(x, x);
  for (size_t i = 0; i < n; ++i) var -= v[i] * v[i];
  if (var < 1e-12) var = 1e-12;
}

double GaussianProcess::ExpectedImprovement(const std::vector<double>& x,
                                            double xi) const {
  double mean, var;
  Predict(x, mean, var);
  double sigma = std::sqrt(var);
  double imp = mean - best_norm_ - xi;
  double z = imp / sigma;
  double cdf = 0.5 * std::erfc(-z / std::sqrt(2.0));
  double pdf = std::exp(-0.5 * z * z) / std::sqrt(2.0 * M_PI);
  return imp * cdf + sigma * pdf;
}

}  // namespace hvd
