#include "hvd/timeline.h"

namespace hvd {

Timeline::~Timeline() { Shutdown(); }

void Timeline::Initialize(const std::string& path, int rank) {
  if (initialized_) return;
  file_.open(path, std::ios::out | std::ios::trunc);
  if (!file_.is_open()) return;
  rank_ = rank;
  start_ = std::chrono::steady_clock::now();
  file_ << "[\n";
  initialized_ = true;
  writer_ = std::thread([this] { WriterLoop(); });
}

int64_t Timeline::NowUs() const {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now() - start_)
      .count();
}

void Timeline::Enqueue(Event e) {
  if (!initialized_) return;
  {
    std::lock_guard<std::mutex> lock(mu_);
    queue_.push_back(std::move(e));
  }
  cv_.notify_one();
}

void Timeline::NegotiateStart(const std::string& name,
                              const std::string& op) {
  Enqueue({'B', name, "NEGOTIATE_" + op, NowUs()});
}

void Timeline::NegotiateEnd(const std::string& name) {
  Enqueue({'E', name, "", NowUs()});
}

void Timeline::Start(const std::string& name, const std::string& op) {
  Enqueue({'B', name, op, NowUs()});
}

void Timeline::ActivityStart(const std::string& name,
                             const std::string& activity) {
  Enqueue({'B', name, activity, NowUs()});
}

void Timeline::ActivityEnd(const std::string& name) {
  Enqueue({'E', name, "", NowUs()});
}

void Timeline::End(const std::string& name) {
  Enqueue({'E', name, "", NowUs()});
}

void Timeline::MarkCycle() { Enqueue({'i', "cycle", "CYCLE", NowUs()}); }

void Timeline::WriterLoop() {
  while (true) {
    std::deque<Event> batch;
    {
      std::unique_lock<std::mutex> lock(mu_);
      cv_.wait(lock, [this] { return !queue_.empty() || shutdown_; });
      if (queue_.empty() && shutdown_) break;
      batch.swap(queue_);
    }
    for (const Event& e : batch) {
      if (!first_event_) file_ << ",\n";
      first_event_ = false;
      file_ << "{\"ph\":\"" << e.phase << "\",\"pid\":" << rank_
            << ",\"tid\":\"" << e.tid << "\",\"ts\":" << e.ts_us;
      if (e.phase != 'E') file_ << ",\"name\":\"" << e.label << "\"";
      if (e.phase == 'i') file_ << ",\"s\":\"g\"";
      file_ << "}";
    }
    file_.flush();
  }
  file_ << "\n]\n";
  file_.close();
}

void Timeline::Shutdown() {
  if (!initialized_) return;
  {
    std::lock_guard<std::mutex> lock(mu_);
    shutdown_ = true;
  }
  cv_.notify_one();
  if (writer_.joinable()) writer_.join();
  initialized_ = false;
}

}  // namespace hvd
