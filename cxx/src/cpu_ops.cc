#include "hvd/cpu_ops.h"

#include <algorithm>
#include <cmath>
#include <cstring>

namespace hvd {

namespace {

// ---- fp16 / bf16 storage types and conversion --------------------------

struct F16 { uint16_t v; };
struct BF16 { uint16_t v; };

inline float HalfToFloat(uint16_t h) {
  uint32_t sign = (h & 0x8000u) << 16;
  uint32_t exp = (h >> 10) & 0x1f;
  uint32_t mant = h & 0x3ffu;
  uint32_t f;
  if (exp == 0) {
    if (mant == 0) {
      f = sign;
    } else {  // subnormal
      exp = 127 - 15 + 1;
      while ((mant & 0x400u) == 0) { mant <<= 1; --exp; }
      mant &= 0x3ffu;
      f = sign | (exp << 23) | (mant << 13);
    }
  } else if (exp == 31) {
    f = sign | 0x7f800000u | (mant << 13);
  } else {
    f = sign | ((exp - 15 + 127) << 23) | (mant << 13);
  }
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToHalf(float x) {
  uint32_t f;
  std::memcpy(&f, &x, 4);
  uint32_t sign = (f >> 16) & 0x8000u;
  uint32_t src_exp = (f >> 23) & 0xffu;
  int32_t exp = static_cast<int32_t>(src_exp) - 127 + 15;
  uint32_t mant = f & 0x7fffffu;
  if (src_exp == 0xffu)  // source inf/NaN; NaN keeps a mantissa bit
    return static_cast<uint16_t>(sign | 0x7c00u | (mant ? 0x200u : 0u));
  if (exp >= 31) return static_cast<uint16_t>(sign | 0x7c00u);  // overflow
  if (exp <= 0) {
    if (exp < -10) return static_cast<uint16_t>(sign);
    mant |= 0x800000u;
    uint32_t shift = static_cast<uint32_t>(14 - exp);
    uint32_t half_mant = mant >> shift;
    uint32_t rem = mant & ((1u << shift) - 1);
    if (rem > (1u << (shift - 1)) ||
        (rem == (1u << (shift - 1)) && (half_mant & 1)))
      ++half_mant;
    return static_cast<uint16_t>(sign | half_mant);
  }
  uint32_t half = sign | (static_cast<uint32_t>(exp) << 10) | (mant >> 13);
  uint32_t rem = mant & 0x1fffu;
  if (rem > 0x1000u || (rem == 0x1000u && (half & 1))) ++half;
  return static_cast<uint16_t>(half);
}

inline float Bf16ToFloat(uint16_t h) {
  uint32_t f = static_cast<uint32_t>(h) << 16;
  float out;
  std::memcpy(&out, &f, 4);
  return out;
}

inline uint16_t FloatToBf16(float x) {
  uint32_t f;
  std::memcpy(&f, &x, 4);
  uint32_t rounded = f + 0x7fffu + ((f >> 16) & 1);  // round-nearest-even
  return static_cast<uint16_t>(rounded >> 16);
}

template <typename T> inline double Load(T v) {
  return static_cast<double>(v);
}
inline double Load(F16 v) { return HalfToFloat(v.v); }
inline double Load(BF16 v) { return Bf16ToFloat(v.v); }

template <typename T> struct Store {
  static T From(double d) { return static_cast<T>(d); }
};
template <> struct Store<F16> {
  static F16 From(double d) {
    return F16{FloatToHalf(static_cast<float>(d))};
  }
};
template <> struct Store<BF16> {
  static BF16 From(double d) {
    return BF16{FloatToBf16(static_cast<float>(d))};
  }
};

template <typename T>
void ReduceIntoT(T* acc, const T* other, int64_t count, ReduceOp op) {
  switch (op) {
    case ReduceOp::SUM:
    case ReduceOp::AVERAGE:
    case ReduceOp::ADASUM:  // summation happens in the adasum schedule
      for (int64_t i = 0; i < count; ++i)
        acc[i] = Store<T>::From(Load(acc[i]) + Load(other[i]));
      break;
    case ReduceOp::MIN:
      for (int64_t i = 0; i < count; ++i)
        if (Load(other[i]) < Load(acc[i])) acc[i] = other[i];
      break;
    case ReduceOp::MAX:
      for (int64_t i = 0; i < count; ++i)
        if (Load(other[i]) > Load(acc[i])) acc[i] = other[i];
      break;
  }
}

template <typename T>
void ScaleT(T* data, int64_t count, double factor) {
  for (int64_t i = 0; i < count; ++i)
    data[i] = Store<T>::From(Load(data[i]) * factor);
}

#define HVD_DISPATCH(dtype, expr_template)                                   \
  switch (dtype) {                                                           \
    case DataType::UINT8:    { using T = uint8_t;  expr_template; break; }   \
    case DataType::INT8:     { using T = int8_t;   expr_template; break; }   \
    case DataType::UINT16:   { using T = uint16_t; expr_template; break; }   \
    case DataType::INT16:    { using T = int16_t;  expr_template; break; }   \
    case DataType::INT32:    { using T = int32_t;  expr_template; break; }   \
    case DataType::INT64:    { using T = int64_t;  expr_template; break; }   \
    case DataType::FLOAT16:  { using T = F16;      expr_template; break; }   \
    case DataType::FLOAT32:  { using T = float;    expr_template; break; }   \
    case DataType::FLOAT64:  { using T = double;   expr_template; break; }   \
    case DataType::BOOL:     { using T = uint8_t;  expr_template; break; }   \
    case DataType::BFLOAT16: { using T = BF16;     expr_template; break; }   \
  }

}  // namespace

void ReduceInto(void* acc, const void* other, int64_t count, DataType dtype,
                ReduceOp op) {
  HVD_DISPATCH(dtype, ReduceIntoT(static_cast<T*>(acc),
                                  static_cast<const T*>(other), count, op));
}

void ScaleInPlace(void* data, int64_t count, DataType dtype, double factor) {
  HVD_DISPATCH(dtype, ScaleT(static_cast<T*>(data), count, factor));
}

namespace {

// chunk layout for the ring schedule: chunk i covers
// [start_el(i), start_el(i) + len_el(i))
struct Chunks {
  int64_t base, rem;
  Chunks(int64_t count, int n) : base(count / n), rem(count % n) {}
  int64_t start(int i) const {
    return static_cast<int64_t>(i) * base + std::min<int64_t>(i, rem);
  }
  int64_t len(int i) const { return base + (i < rem ? 1 : 0); }
};

}  // namespace

namespace {

Group TrivialGroup(int rank, int size) {
  Group grp;
  grp.members.resize(size);
  for (int i = 0; i < size; ++i) grp.members[i] = i;
  grp.pos = rank;
  return grp;
}

}  // namespace

Status GroupRingAllreduce(PeerMesh& mesh, const Group& grp, void* data,
                          int64_t count, DataType dtype, ReduceOp op) {
  int size = grp.size();
  if (size == 1) {
    return Status::OK();
  }
  // ring allreduce = ring reduce-scatter (position p ends owning reduced
  // chunk p) + ring allgatherv of the owned chunks — one implementation
  // of the N-1-step reduce schedule, shared with the standalone
  // reduce-scatter op.
  size_t esz = DataTypeSize(dtype);
  Chunks ch(count, size);
  std::vector<int64_t> counts(size);
  for (int i = 0; i < size; ++i) counts[i] = ch.len(i);
  ReduceOp wire_op = (op == ReduceOp::AVERAGE) ? ReduceOp::SUM : op;
  std::vector<uint8_t> own(counts[grp.pos] * esz);
  Status st = GroupRingReduceScatter(mesh, grp, data, counts, dtype,
                                     wire_op, own.data());
  if (!st.ok()) return st;
  st = GroupRingAllgatherv(mesh, grp, own.data(), counts, dtype, data);
  if (!st.ok()) return st;
  if (op == ReduceOp::AVERAGE)
    ScaleInPlace(data, count, dtype, 1.0 / size);
  return Status::OK();
}

Status RingAllreduce(PeerMesh& mesh, int rank, int size, void* data,
                     int64_t count, DataType dtype, ReduceOp op) {
  return GroupRingAllreduce(mesh, TrivialGroup(rank, size), data, count,
                            dtype, op);
}

Status GroupRingReduceScatter(PeerMesh& mesh, const Group& grp, void* data,
                              const std::vector<int64_t>& counts,
                              DataType dtype, ReduceOp op, void* output) {
  int size = grp.size(), pos = grp.pos;
  size_t esz = DataTypeSize(dtype);
  uint8_t* bytes = static_cast<uint8_t*>(data);
  std::vector<int64_t> displs(size, 0);
  for (int i = 1; i < size; ++i) displs[i] = displs[i - 1] + counts[i - 1];

  if (size > 1) {
    int64_t max_count = 0;
    for (int64_t c : counts) max_count = std::max(max_count, c);
    std::vector<uint8_t> tmp(max_count * esz);
    int next = grp.next();
    int prev = grp.prev();
    // schedule shifted one chunk vs the allreduce phase so position p ends
    // owning chunk p (not p+1): step s sends chunk (p-s-1), reduces
    // chunk (p-s-2); after N-1 steps the fully reduced chunk is p's own.
    for (int s = 0; s < size - 1; ++s) {
      int send_c = (pos - s - 1 + 2 * size) % size;
      int recv_c = (pos - s - 2 + 2 * size) % size;
      Status st = mesh.RingStep(next, prev, bytes + displs[send_c] * esz,
                                counts[send_c] * esz, tmp.data(),
                                counts[recv_c] * esz);
      if (!st.ok()) return st;
      ReduceInto(bytes + displs[recv_c] * esz, tmp.data(), counts[recv_c],
                 dtype, op);
    }
  }
  std::memcpy(output, bytes + displs[pos] * esz, counts[pos] * esz);
  if (op == ReduceOp::AVERAGE)
    ScaleInPlace(output, counts[pos], dtype, 1.0 / size);
  return Status::OK();
}

Status RingReduceScatter(PeerMesh& mesh, int rank, int size, void* data,
                         const std::vector<int64_t>& counts, DataType dtype,
                         ReduceOp op, void* output) {
  return GroupRingReduceScatter(mesh, TrivialGroup(rank, size), data,
                                counts, dtype, op, output);
}

Status GroupRingAllgatherv(PeerMesh& mesh, const Group& grp,
                           const void* input,
                           const std::vector<int64_t>& counts,
                           DataType dtype, void* output) {
  int size = grp.size(), pos = grp.pos;
  size_t esz = DataTypeSize(dtype);
  uint8_t* out = static_cast<uint8_t*>(output);
  std::vector<int64_t> displs(size, 0);
  for (int i = 1; i < size; ++i) displs[i] = displs[i - 1] + counts[i - 1];
  // hierarchical phase 2 gathers in place: my block already sits at its
  // output slot, so the self-copy is skipped
  if (static_cast<const void*>(out + displs[pos] * esz) != input)
    std::memcpy(out + displs[pos] * esz, input, counts[pos] * esz);
  if (size == 1) return Status::OK();
  int next = grp.next();
  int prev = grp.prev();
  for (int s = 0; s < size - 1; ++s) {
    int send_b = (pos - s + size) % size;
    int recv_b = (pos - s - 1 + size) % size;
    Status st = mesh.RingStep(next, prev, out + displs[send_b] * esz,
                              counts[send_b] * esz,
                              out + displs[recv_b] * esz,
                              counts[recv_b] * esz);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

Status RingAllgatherv(PeerMesh& mesh, int rank, int size, const void* input,
                      const std::vector<int64_t>& counts, DataType dtype,
                      void* output) {
  return GroupRingAllgatherv(mesh, TrivialGroup(rank, size), input, counts,
                             dtype, output);
}

Status GroupBroadcast(PeerMesh& mesh, const Group& grp, void* data,
                      int64_t count, DataType dtype, int root_pos) {
  if (grp.size() == 1) return Status::OK();
  size_t nbytes = count * DataTypeSize(dtype);
  if (grp.pos == root_pos) {
    for (int i = 0; i < grp.size(); ++i) {
      if (i == root_pos) continue;
      Status st = mesh.SendTo(grp.members[i], data, nbytes);
      if (!st.ok()) return st;
    }
    return Status::OK();
  }
  return mesh.RecvFrom(grp.members[root_pos], data, nbytes);
}

Status Broadcast(PeerMesh& mesh, int rank, int size, void* data,
                 int64_t count, DataType dtype, int root) {
  return GroupBroadcast(mesh, TrivialGroup(rank, size), data, count, dtype,
                        root);
}

// ---- hierarchical (2-level) composites ---------------------------------

Status HierarchicalAllreduce(PeerMesh& mesh, const Topology& topo,
                             void* data, int64_t count, DataType dtype,
                             ReduceOp op, int average_denom) {
  size_t esz = DataTypeSize(dtype);
  ReduceOp wire_op = (op == ReduceOp::AVERAGE) ? ReduceOp::SUM : op;
  Group local = topo.LocalGroup();
  Chunks ch(count, local.size());
  std::vector<int64_t> counts(local.size());
  for (int i = 0; i < local.size(); ++i) counts[i] = ch.len(i);

  // 1. intra-host reduce-scatter: local rank r ends owning the host-sum
  //    of chunk r
  std::vector<uint8_t> own(counts[topo.local_rank] * esz);
  Status st = GroupRingReduceScatter(mesh, local, data, counts, dtype,
                                     wire_op, own.data());
  if (!st.ok()) return st;
  // 2. cross-host allreduce of the owned chunk; every local rank drives
  //    its own cross ring concurrently (disjoint peer sets)
  st = GroupRingAllreduce(mesh, topo.CrossGroup(), own.data(),
                          counts[topo.local_rank], dtype, wire_op);
  if (!st.ok()) return st;
  // 3. intra-host allgather of globally-reduced chunks
  st = GroupRingAllgatherv(mesh, local, own.data(), counts, dtype, data);
  if (!st.ok()) return st;
  if (op == ReduceOp::AVERAGE && average_denom > 0)
    ScaleInPlace(data, count, dtype, 1.0 / average_denom);
  return Status::OK();
}

Status HierarchicalAllgatherv(PeerMesh& mesh, const Topology& topo,
                              const void* input,
                              const std::vector<int64_t>& counts,
                              DataType dtype, void* output) {
  size_t esz = DataTypeSize(dtype);
  int L = topo.local_size, C = topo.cross_size;
  std::vector<int64_t> displs(topo.size, 0);
  for (int i = 1; i < topo.size; ++i) displs[i] = displs[i - 1] + counts[i - 1];
  int64_t total = displs[topo.size - 1] + counts[topo.size - 1];
  uint8_t* out = static_cast<uint8_t*>(output);

  // 1. intra-host allgatherv straight into this host's (contiguous) block
  //    of the output buffer
  Group local = topo.LocalGroup();
  std::vector<int64_t> lcounts(L);
  for (int i = 0; i < L; ++i) lcounts[i] = counts[topo.cross_rank * L + i];
  uint8_t* host_block = out + displs[topo.cross_rank * L] * esz;
  Status st = GroupRingAllgatherv(mesh, local, input, lcounts, dtype,
                                  host_block);
  if (!st.ok()) return st;

  // 2. host leaders exchange whole host blocks — the only cross-host
  //    traffic, once per HOST instead of once per rank
  if (topo.local_rank == 0) {
    std::vector<int64_t> hcounts(C, 0);
    for (int h = 0; h < C; ++h)
      for (int i = 0; i < L; ++i) hcounts[h] += counts[h * L + i];
    st = GroupRingAllgatherv(mesh, topo.CrossGroup(), host_block, hcounts,
                             dtype, out);
    if (!st.ok()) return st;
  }
  // 3. full result fans out intra-host from the leader (the shared-memory
  //    window bcast of the reference, over loopback TCP here)
  return GroupBroadcast(mesh, local, out, total, dtype, 0);
}

Status AllToAll(PeerMesh& mesh, int rank, int size, const void* input,
                int64_t block, DataType dtype, void* output) {
  size_t bsz = block * DataTypeSize(dtype);
  const uint8_t* in = static_cast<const uint8_t*>(input);
  uint8_t* out = static_cast<uint8_t*>(output);
  std::memcpy(out + rank * bsz, in + rank * bsz, bsz);
  for (int r = 1; r < size; ++r) {
    int send_to = (rank + r) % size;
    int recv_from = (rank - r + size) % size;
    Status st = mesh.RingStep(send_to, recv_from, in + send_to * bsz, bsz,
                              out + recv_from * bsz, bsz);
    if (!st.ok()) return st;
  }
  return Status::OK();
}

// ---- Adasum ------------------------------------------------------------

namespace {

// Orientation matters: `a` is always the bit-0 ("low") group's vector and
// `b` the bit-1 group's, on BOTH sides of a pair — otherwise the group
// norms |a|^2 and |b|^2 get mixed across ranks. `own_is_a` says which of
// (own fragment, received fragment) plays the role of a.
template <typename T>
void PartialDots(const T* own, const T* other, int64_t n, bool own_is_a,
                 double out[3]) {
  double dot = 0, n_own = 0, n_other = 0;
  for (int64_t i = 0; i < n; ++i) {
    double x = Load(own[i]), y = Load(other[i]);
    dot += x * y;
    n_own += x * x;
    n_other += y * y;
  }
  out[0] = dot;
  out[1] = own_is_a ? n_own : n_other;   // |a|^2
  out[2] = own_is_a ? n_other : n_own;   // |b|^2
}

template <typename T>
void Combine(T* own, const T* other, int64_t n, bool own_is_a,
             const double dots[3]) {
  // result = a*(1 - dot/(2|a|^2)) + b*(1 - dot/(2|b|^2)) — the
  // scale-insensitive pairwise merge (reference adasum.h:331+).
  double ca = dots[1] > 0 ? 1.0 - dots[0] / (2.0 * dots[1]) : 1.0;
  double cb = dots[2] > 0 ? 1.0 - dots[0] / (2.0 * dots[2]) : 1.0;
  double c_own = own_is_a ? ca : cb;
  double c_other = own_is_a ? cb : ca;
  for (int64_t i = 0; i < n; ++i)
    own[i] = Store<T>::From(c_own * Load(own[i]) +
                            c_other * Load(other[i]));
}

struct LevelRecord {
  int partner;
  int64_t prev_start, prev_len;
  int64_t start, len;  // fragment kept after the exchange
};

// recursive-doubling sum of 3 doubles over the aligned subgroup of
// `group_size` positions containing `grp.pos` (positions, not global
// ranks — the same schedule runs intra-host or cross-host)
Status GroupSumDots(PeerMesh& mesh, const Group& grp, int group_size,
                    double dots[3]) {
  for (int e = 1; e < group_size; e <<= 1) {
    int partner = grp.members[grp.pos ^ e];
    double theirs[3];
    Status st = mesh.SendRecv(partner, dots, sizeof(double) * 3, theirs,
                              sizeof(double) * 3);
    if (!st.ok()) return st;
    for (int i = 0; i < 3; ++i) dots[i] += theirs[i];
  }
  return Status::OK();
}

template <typename T>
Status AdasumT(PeerMesh& mesh, const Group& grp, T* data, int64_t count) {
  int size = grp.size(), pos = grp.pos;
  std::vector<T> tmp(count);
  std::vector<LevelRecord> stack;
  int64_t start = 0, len = count;

  for (int d = 1; d < size; d <<= 1) {
    int partner = grp.members[pos ^ d];
    int64_t low_len = len / 2;
    int64_t high_len = len - low_len;
    bool keep_low = (pos & d) == 0;
    int64_t my_start = keep_low ? start : start + low_len;
    int64_t my_len = keep_low ? low_len : high_len;
    int64_t send_start = keep_low ? start + low_len : start;
    int64_t send_len = keep_low ? high_len : low_len;

    // exchange halves: afterwards tmp[0..my_len) holds the partner's copy
    // of MY half of the vector
    Status st = mesh.SendRecv(partner, data + send_start,
                              send_len * sizeof(T), tmp.data(),
                              my_len * sizeof(T));
    if (!st.ok()) return st;

    bool own_is_a = (pos & d) == 0;  // bit-0 side is the "a" group
    double dots[3];
    PartialDots(data + my_start, tmp.data(), my_len, own_is_a, dots);
    st = GroupSumDots(mesh, grp, d << 1, dots);
    if (!st.ok()) return st;
    Combine(data + my_start, tmp.data(), my_len, own_is_a, dots);

    stack.push_back({partner, start, len, my_start, my_len});
    start = my_start;
    len = my_len;
  }

  // reconstruct: walk back up, exchanging fragments with each partner
  for (auto it = stack.rbegin(); it != stack.rend(); ++it) {
    int64_t other_start =
        (it->start == it->prev_start) ? it->start + it->len : it->prev_start;
    int64_t other_len = it->prev_len - it->len;
    Status st = mesh.SendRecv(it->partner, data + it->start,
                              it->len * sizeof(T), data + other_start,
                              other_len * sizeof(T));
    if (!st.ok()) return st;
    start = it->prev_start;
    len = it->prev_len;
  }
  return Status::OK();
}

Status GroupAdasum(PeerMesh& mesh, const Group& grp, void* data,
                   int64_t count, DataType dtype) {
  if (grp.size() == 1) return Status::OK();
  if ((grp.size() & (grp.size() - 1)) != 0)
    return Status::InvalidArgument(
        "Adasum requires a power-of-2 number of ranks (got " +
        std::to_string(grp.size()) + ")");
  switch (dtype) {
    case DataType::FLOAT16:
      return AdasumT(mesh, grp, static_cast<F16*>(data), count);
    case DataType::BFLOAT16:
      return AdasumT(mesh, grp, static_cast<BF16*>(data), count);
    case DataType::FLOAT32:
      return AdasumT(mesh, grp, static_cast<float*>(data), count);
    case DataType::FLOAT64:
      return AdasumT(mesh, grp, static_cast<double*>(data), count);
    default:
      return Status::InvalidArgument("Adasum supports float dtypes only");
  }
}

}  // namespace

Status AdasumAllreduce(PeerMesh& mesh, ControlPlane& control, int rank,
                       int size, void* data, int64_t count, DataType dtype) {
  (void)control;
  return GroupAdasum(mesh, TrivialGroup(rank, size), data, count, dtype);
}

Status HierarchicalAdasumAllreduce(PeerMesh& mesh, const Topology& topo,
                                   void* data, int64_t count,
                                   DataType dtype) {
  // The reference's production Adasum mode
  // (adasum_cuda_operations.cc:96-260): intra-node ReduceScatter (sum),
  // Adasum across nodes run independently on each local rank's chunk
  // (the reference's cross-node VHDD starts at start_level = local_size,
  // so each chunk gets its own combine coefficients), intra-node
  // Allgather. The final 1/local_size is the divisor the reference
  // applies in its framework layer (torch/mpi_ops.py:104-110); folded in
  // here so every adapter sees the same user-visible result.
  if ((topo.cross_size & (topo.cross_size - 1)) != 0)
    return Status::InvalidArgument(
        "hierarchical Adasum requires a power-of-2 number of hosts (got " +
        std::to_string(topo.cross_size) + ")");
  switch (dtype) {
    case DataType::FLOAT16:
    case DataType::BFLOAT16:
    case DataType::FLOAT32:
    case DataType::FLOAT64:
      break;
    default:
      return Status::InvalidArgument("Adasum supports float dtypes only");
  }
  size_t esz = DataTypeSize(dtype);
  Group local = topo.LocalGroup();
  Chunks ch(count, local.size());
  std::vector<int64_t> counts(local.size());
  for (int i = 0; i < local.size(); ++i) counts[i] = ch.len(i);

  // 1. intra-host reduce-scatter (SUM): local rank r owns the host-sum
  //    of chunk r
  std::vector<uint8_t> own(counts[topo.local_rank] * esz);
  Status st = GroupRingReduceScatter(mesh, local, data, counts, dtype,
                                     ReduceOp::SUM, own.data());
  if (!st.ok()) return st;
  // 2. per-chunk Adasum across hosts (every local rank drives its own
  //    cross tree concurrently, disjoint peer sets)
  st = GroupAdasum(mesh, topo.CrossGroup(), own.data(),
                   counts[topo.local_rank], dtype);
  if (!st.ok()) return st;
  // 3. intra-host allgather of the combined chunks
  st = GroupRingAllgatherv(mesh, local, own.data(), counts, dtype, data);
  if (!st.ok()) return st;
  // 4. local_size division (reference framework-layer divisor)
  ScaleInPlace(data, count, dtype, 1.0 / local.size());
  return Status::OK();
}

}  // namespace hvd
