#include "hvd/stall_inspector.h"

#include <algorithm>
#include <cstdio>
#include <sstream>

namespace hvd {

bool StallInspector::Check(
    const std::vector<std::pair<std::string, std::vector<int>>>& pending,
    int world_size) {
  auto now = std::chrono::steady_clock::now();
  // prune entries that negotiated away
  std::unordered_map<std::string, std::chrono::steady_clock::time_point>
      kept;
  stalled_.clear();
  bool shutdown = false;
  std::ostringstream warn;
  for (const auto& p : pending) {
    auto it = first_seen_.find(p.first);
    auto t0 = it == first_seen_.end() ? now : it->second;
    kept[p.first] = t0;
    double age = std::chrono::duration<double>(now - t0).count();
    if (age > warn_sec_) {
      stalled_.push_back(p.first);
      std::vector<int> missing;
      for (int r = 0; r < world_size; ++r)
        if (std::find(p.second.begin(), p.second.end(), r) ==
            p.second.end())
          missing.push_back(r);
      warn << "  " << p.first << " [missing ranks:";
      for (int r : missing) warn << " " << r;
      warn << "]\n";
    }
    if (shutdown_sec_ > 0 && age > shutdown_sec_) shutdown = true;
  }
  first_seen_ = std::move(kept);
  if (!stalled_.empty() &&
      std::chrono::duration<double>(now - last_warn_).count() > warn_sec_) {
    last_warn_ = now;
    std::fprintf(stderr,
                 "[horovod_tpu] WARNING: one or more tensors were submitted "
                 "by a subset of ranks and are waiting on the rest for "
                 "more than %.0f s:\n%s",
                 warn_sec_, warn.str().c_str());
  }
  return shutdown;
}

}  // namespace hvd
