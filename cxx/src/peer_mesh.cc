#include "hvd/peer_mesh.h"

#include <poll.h>
#include <sys/socket.h>

#include <cstring>

namespace hvd {

Status Progress(std::vector<Transfer>& transfers) {
  while (true) {
    std::vector<struct pollfd> pfds;
    std::vector<size_t> idx;
    for (size_t i = 0; i < transfers.size(); ++i) {
      Transfer& t = transfers[i];
      if (t.done >= t.len) continue;
      struct pollfd p;
      p.fd = t.fd;
      p.events = t.is_send ? POLLOUT : POLLIN;
      p.revents = 0;
      pfds.push_back(p);
      idx.push_back(i);
    }
    if (pfds.empty()) return Status::OK();
    int rv = ::poll(pfds.data(), pfds.size(), 60000);
    if (rv < 0) {
      if (errno == EINTR) continue;
      return Status::Unknown(std::string("poll failed: ") +
                             std::strerror(errno));
    }
    if (rv == 0) return Status::Unknown("data-plane transfer timed out");
    for (size_t k = 0; k < pfds.size(); ++k) {
      if (pfds[k].revents == 0) continue;
      Transfer& t = transfers[idx[k]];
      if (pfds[k].revents & (POLLERR | POLLHUP | POLLNVAL)) {
        // HUP with pending inbound data is still readable; try the IO and
        // let it report the real error.
      }
      ssize_t n;
      if (t.is_send) {
        n = ::send(t.fd, t.send_buf + t.done, t.len - t.done, MSG_NOSIGNAL);
      } else {
        n = ::recv(t.fd, t.recv_buf + t.done, t.len - t.done, 0);
        if (n == 0) return Status::Aborted("peer closed connection");
      }
      if (n < 0) {
        if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
          continue;
        return Status::Unknown(std::string(t.is_send ? "send" : "recv") +
                               " failed: " + std::strerror(errno));
      }
      t.done += static_cast<size_t>(n);
    }
  }
}

PeerMesh::PeerMesh(int rank, int size)
    : rank_(rank),
      size_(size),
      sent_bytes_(new std::atomic<int64_t>[size > 0 ? size : 1]) {
  for (int i = 0; i < size_; ++i) sent_bytes_[i].store(0);
}

int64_t PeerMesh::bytes_sent_to(int peer) const {
  if (peer < 0 || peer >= size_) return 0;
  return sent_bytes_[peer].load();
}

PeerMesh::~PeerMesh() { Shutdown(); }

Status PeerMesh::Start() {
  server_ = std::make_unique<TcpServer>(0);
  if (!server_->ok()) return Status::Unknown("peer mesh: cannot listen");
  accept_thread_ = std::thread([this] { AcceptLoop(); });
  return Status::OK();
}

int PeerMesh::port() const { return server_ ? server_->port() : 0; }

void PeerMesh::SetRoster(std::vector<PeerInfo> roster) {
  std::lock_guard<std::mutex> lock(mu_);
  roster_ = std::move(roster);
}

void PeerMesh::AcceptLoop() {
  while (true) {
    {
      std::lock_guard<std::mutex> lock(mu_);
      if (shutdown_) return;
    }
    auto conn = server_->Accept(0.2);
    if (!conn) continue;
    // Deadline on the hello frame: a connected-but-silent (or dripping)
    // peer must not block mesh bring-up for everyone else.
    std::vector<uint8_t> hello;
    if (!conn->RecvFrameDeadline(hello, 5.0).ok() || hello.size() < 4)
      continue;
    Reader r(hello);
    int peer = r.i32();
    conn->SetNonBlocking();
    {
      std::lock_guard<std::mutex> lock(mu_);
      // Reject out-of-range ranks and hellos for ranks already
      // connected — an arbitrary claimed rank must not hijack an
      // existing peer's connection entry.
      if (peer < 0 || peer >= size_ || conns_.count(peer)) continue;
      conns_[peer] = std::move(conn);
    }
    cv_.notify_all();
  }
}

Status PeerMesh::Get(int peer, TcpConnection** out) {
  std::unique_lock<std::mutex> lock(mu_);
  auto it = conns_.find(peer);
  if (it != conns_.end()) {
    *out = it->second.get();
    return Status::OK();
  }
  if (rank_ < peer) {
    // initiator
    if (roster_.empty() || peer >= static_cast<int>(roster_.size()))
      return Status::Precondition("peer mesh: roster not set");
    PeerInfo info = roster_[peer];
    lock.unlock();
    auto conn = TcpConnection::Connect(info.host, info.data_port, 60.0);
    if (!conn)
      return Status::Unknown("peer mesh: cannot connect to rank " +
                             std::to_string(peer));
    Writer w;
    w.i32(rank_);
    Status s = conn->SendFrame(w.data());
    if (!s.ok()) return s;
    conn->SetNonBlocking();
    lock.lock();
    conns_[peer] = std::move(conn);
    *out = conns_[peer].get();
    return Status::OK();
  }
  // acceptor: wait for the initiator to dial in
  bool ok = cv_.wait_for(lock, std::chrono::seconds(60), [&] {
    return conns_.count(peer) > 0 || shutdown_;
  });
  if (!ok || shutdown_)
    return Status::Unknown("peer mesh: timeout waiting for rank " +
                           std::to_string(peer));
  *out = conns_[peer].get();
  return Status::OK();
}

Status PeerMesh::SendTo(int peer, const void* data, size_t len) {
  TcpConnection* c;
  Status s = Get(peer, &c);
  if (!s.ok()) return s;
  std::vector<Transfer> ts(1);
  ts[0] = {c->fd(), true, static_cast<const uint8_t*>(data), nullptr, len, 0};
  sent_bytes_[peer].fetch_add(static_cast<int64_t>(len));
  return Progress(ts);
}

Status PeerMesh::RecvFrom(int peer, void* data, size_t len) {
  TcpConnection* c;
  Status s = Get(peer, &c);
  if (!s.ok()) return s;
  std::vector<Transfer> ts(1);
  ts[0] = {c->fd(), false, nullptr, static_cast<uint8_t*>(data), len, 0};
  return Progress(ts);
}

Status PeerMesh::SendRecv(int peer, const void* send, size_t send_len,
                          void* recv, size_t recv_len) {
  TcpConnection* c;
  Status s = Get(peer, &c);
  if (!s.ok()) return s;
  std::vector<Transfer> ts(2);
  ts[0] = {c->fd(), true, static_cast<const uint8_t*>(send), nullptr,
           send_len, 0};
  ts[1] = {c->fd(), false, nullptr, static_cast<uint8_t*>(recv), recv_len, 0};
  sent_bytes_[peer].fetch_add(static_cast<int64_t>(send_len));
  return Progress(ts);
}

Status PeerMesh::RingStep(int next, int prev, const void* send,
                          size_t send_len, void* recv, size_t recv_len) {
  TcpConnection *cn, *cp;
  Status s = Get(next, &cn);
  if (!s.ok()) return s;
  s = Get(prev, &cp);
  if (!s.ok()) return s;
  std::vector<Transfer> ts(2);
  ts[0] = {cn->fd(), true, static_cast<const uint8_t*>(send), nullptr,
           send_len, 0};
  ts[1] = {cp->fd(), false, nullptr, static_cast<uint8_t*>(recv), recv_len,
           0};
  sent_bytes_[next].fetch_add(static_cast<int64_t>(send_len));
  return Progress(ts);
}

void PeerMesh::Shutdown() {
  {
    std::lock_guard<std::mutex> lock(mu_);
    if (shutdown_) return;
    shutdown_ = true;
  }
  cv_.notify_all();
  if (accept_thread_.joinable()) accept_thread_.join();
  std::lock_guard<std::mutex> lock(mu_);
  conns_.clear();
  server_.reset();
}

}  // namespace hvd
