#include "hvd/message.h"

#include <cstring>

namespace hvd {

// Scale factors ride the wire bit-exactly: every rank (and the response
// cache's parameter comparison) must see the identical double, so the
// codec must not round-trip through any lossy representation.
static int64_t DoubleBits(double d) {
  int64_t b;
  std::memcpy(&b, &d, sizeof(b));
  return b;
}

static double BitsToDouble(int64_t b) {
  double d;
  std::memcpy(&d, &b, sizeof(d));
  return d;
}

const char* RequestTypeName(Request::Type t) {
  switch (t) {
    case Request::ALLREDUCE: return "ALLREDUCE";
    case Request::ALLGATHER: return "ALLGATHER";
    case Request::BROADCAST: return "BROADCAST";
    case Request::JOIN: return "JOIN";
    case Request::ADASUM: return "ADASUM";
    case Request::ALLTOALL: return "ALLTOALL";
    case Request::REDUCESCATTER: return "REDUCESCATTER";
    case Request::BARRIER: return "BARRIER";
  }
  return "UNKNOWN";
}

void Request::Serialize(Writer& w) const {
  w.u8(type);
  w.i32(request_rank);
  w.u8(static_cast<uint8_t>(dtype));
  w.str(tensor_name);
  w.i32(root_rank);
  w.i32(shape.ndim());
  for (int i = 0; i < shape.ndim(); ++i) w.i64(shape.dim(i));
  w.i64(DoubleBits(prescale_factor));
  w.i64(DoubleBits(postscale_factor));
  w.u8(reduce_op);
}

Request Request::Deserialize(Reader& r) {
  Request q;
  q.type = static_cast<Type>(r.u8());
  q.request_rank = r.i32();
  q.dtype = static_cast<DataType>(r.u8());
  q.tensor_name = r.str();
  q.root_rank = r.i32();
  int ndim = r.i32();
  for (int i = 0; i < ndim; ++i) q.shape.AddDim(r.i64());
  q.prescale_factor = BitsToDouble(r.i64());
  q.postscale_factor = BitsToDouble(r.i64());
  q.reduce_op = r.u8();
  return q;
}

void Response::Serialize(Writer& w) const {
  w.u8(type);
  w.i32(static_cast<int32_t>(tensor_names.size()));
  for (const auto& n : tensor_names) w.str(n);
  w.str(error_message);
  w.i32(static_cast<int32_t>(tensor_sizes.size()));
  for (int64_t s : tensor_sizes) w.i64(s);
  w.u8(static_cast<uint8_t>(dtype));
  w.u8(reduce_op);
  w.i32(active_ranks);
}

Response Response::Deserialize(Reader& r) {
  Response p;
  p.type = static_cast<Type>(r.u8());
  int32_t n = r.i32();
  p.tensor_names.reserve(n);
  for (int32_t i = 0; i < n; ++i) p.tensor_names.push_back(r.str());
  p.error_message = r.str();
  int32_t m = r.i32();
  p.tensor_sizes.reserve(m);
  for (int32_t i = 0; i < m; ++i) p.tensor_sizes.push_back(r.i64());
  p.dtype = static_cast<DataType>(r.u8());
  p.reduce_op = r.u8();
  p.active_ranks = r.i32();
  return p;
}

namespace {

// cache bitvectors are sparse in practice; trailing zero words elided
void WriteBits(Writer& w, const std::vector<uint64_t>& bits) {
  size_t n = bits.size();
  while (n > 0 && bits[n - 1] == 0) --n;
  w.i32(static_cast<int32_t>(n));
  for (size_t i = 0; i < n; ++i)
    w.i64(static_cast<int64_t>(bits[i]));
}

std::vector<uint64_t> ReadBits(Reader& r) {
  int32_t n = r.i32();
  std::vector<uint64_t> bits(n);
  for (int32_t i = 0; i < n; ++i)
    bits[i] = static_cast<uint64_t>(r.i64());
  return bits;
}

}  // namespace

std::vector<uint8_t> RequestList::Serialize() const {
  Writer w;
  w.u8(shutdown ? 1 : 0);
  WriteBits(w, cache_bits);
  w.i32(static_cast<int32_t>(requests.size()));
  for (const auto& q : requests) q.Serialize(w);
  return w.take();
}

RequestList RequestList::Deserialize(const std::vector<uint8_t>& buf) {
  Reader r(buf);
  RequestList l;
  l.shutdown = r.u8() != 0;
  l.cache_bits = ReadBits(r);
  int32_t n = r.i32();
  l.requests.reserve(n);
  for (int32_t i = 0; i < n; ++i)
    l.requests.push_back(Request::Deserialize(r));
  return l;
}

std::vector<uint8_t> ResponseList::Serialize() const {
  Writer w;
  w.u8(shutdown ? 1 : 0);
  w.u8(has_tuned_params ? 1 : 0);
  w.i64(tuned_fusion_threshold);
  w.i64(DoubleBits(tuned_cycle_time_ms));
  w.u8(tuned_hierarchical);
  w.u8(tuned_cache);
  WriteBits(w, cache_hits);
  w.i32(static_cast<int32_t>(cache_invalid.size()));
  for (uint32_t b : cache_invalid) w.i32(static_cast<int32_t>(b));
  w.i32(active_ranks);
  w.i32(static_cast<int32_t>(responses.size()));
  for (const auto& p : responses) p.Serialize(w);
  return w.take();
}

ResponseList ResponseList::Deserialize(const std::vector<uint8_t>& buf) {
  Reader r(buf);
  ResponseList l;
  l.shutdown = r.u8() != 0;
  l.has_tuned_params = r.u8() != 0;
  l.tuned_fusion_threshold = r.i64();
  l.tuned_cycle_time_ms = BitsToDouble(r.i64());
  l.tuned_hierarchical = r.u8();
  l.tuned_cache = r.u8();
  l.cache_hits = ReadBits(r);
  int32_t ninv = r.i32();
  l.cache_invalid.reserve(ninv);
  for (int32_t i = 0; i < ninv; ++i)
    l.cache_invalid.push_back(static_cast<uint32_t>(r.i32()));
  l.active_ranks = r.i32();
  int32_t n = r.i32();
  l.responses.reserve(n);
  for (int32_t i = 0; i < n; ++i)
    l.responses.push_back(Response::Deserialize(r));
  return l;
}

}  // namespace hvd
