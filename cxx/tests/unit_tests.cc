// Unit tests for the pure in-process components (no sockets): message
// round-trip, negotiator validation + fusion planning, response cache LRU,
// stall inspector, reduction kernels. Built and run by `make test`.
#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstring>

#include "hvd/cpu_ops.h"
#include "hvd/gaussian_process.h"
#include "hvd/message.h"
#include "hvd/negotiator.h"
#include "hvd/parameter_manager.h"
#include "hvd/response_cache.h"
#include "hvd/stall_inspector.h"

using namespace hvd;

static int failures = 0;
#define CHECK(cond)                                                       \
  do {                                                                    \
    if (!(cond)) {                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      ++failures;                                                         \
    }                                                                     \
  } while (0)

static Request MakeReq(const std::string& name, int rank,
                       Request::Type type = Request::ALLREDUCE,
                       DataType dt = DataType::FLOAT32,
                       std::vector<int64_t> dims = {4, 2}) {
  Request q;
  q.type = type;
  q.request_rank = rank;
  q.dtype = dt;
  q.tensor_name = name;
  q.shape = TensorShape(std::move(dims));
  return q;
}

static void TestMessageRoundtrip() {
  RequestList rl;
  rl.shutdown = true;
  Request q = MakeReq("grad/w1", 3);
  q.prescale_factor = 0.5;
  q.reduce_op = 1;
  rl.requests.push_back(q);
  auto bytes = rl.Serialize();
  RequestList back = RequestList::Deserialize(bytes);
  CHECK(back.shutdown);
  CHECK(back.requests.size() == 1);
  CHECK(back.requests[0].tensor_name == "grad/w1");
  CHECK(back.requests[0].request_rank == 3);
  CHECK(back.requests[0].shape.dims() == std::vector<int64_t>({4, 2}));
  CHECK(std::abs(back.requests[0].prescale_factor - 0.5) < 1e-12);
  CHECK(back.requests[0].reduce_op == 1);

  // scale factors must round-trip BIT-exactly (0.1 is not representable;
  // a lossy codec would defeat response-cache parameter comparison)
  Request q2 = MakeReq("grad/w2", 0);
  q2.prescale_factor = 0.1;
  q2.postscale_factor = 3.0e300;
  RequestList rl2;
  rl2.requests.push_back(q2);
  auto bytes2 = rl2.Serialize();
  RequestList back2 = RequestList::Deserialize(bytes2);
  CHECK(back2.requests[0].prescale_factor == 0.1);
  CHECK(back2.requests[0].postscale_factor == 3.0e300);

  ResponseList pl;
  Response p;
  p.type = Response::ALLGATHER;
  p.tensor_names = {"a", "b"};
  p.tensor_sizes = {1, 2, 3};
  p.dtype = DataType::BFLOAT16;
  p.active_ranks = 7;
  pl.responses.push_back(p);
  auto pb = pl.Serialize();
  ResponseList pback = ResponseList::Deserialize(pb);
  CHECK(pback.responses[0].tensor_names.size() == 2);
  CHECK(pback.responses[0].tensor_sizes == std::vector<int64_t>({1, 2, 3}));
  CHECK(pback.responses[0].dtype == DataType::BFLOAT16);
  CHECK(pback.responses[0].active_ranks == 7);
}

static void TestNegotiatorReadiness() {
  Negotiator n(3);
  auto r1 = n.AddRequests({MakeReq("t", 0)}, 0);
  CHECK(r1.empty());
  auto r2 = n.AddRequests({MakeReq("t", 1)}, 0);
  CHECK(r2.empty());
  auto r3 = n.AddRequests({MakeReq("t", 2)}, 0);
  CHECK(r3.size() == 1 && r3[0] == "t");
  Response resp = n.BuildResponse("t");
  CHECK(resp.type == Response::ALLREDUCE);
  CHECK(resp.error_message.empty());
  CHECK(resp.tensor_sizes == std::vector<int64_t>({8}));
  CHECK(!n.has_pending());
}

static void TestNegotiatorValidation() {
  Negotiator n(2);
  n.AddRequests({MakeReq("t", 0, Request::ALLREDUCE, DataType::FLOAT32)}, 0);
  auto ready = n.AddRequests(
      {MakeReq("t", 1, Request::ALLREDUCE, DataType::FLOAT64)}, 0);
  CHECK(ready.size() == 1);
  Response resp = n.BuildResponse("t");
  CHECK(resp.type == Response::ERROR);
  CHECK(resp.error_message.find("mismatched dtypes") != std::string::npos);

  // allgather with differing first dims is legal
  Negotiator n2(2);
  n2.AddRequests({MakeReq("g", 0, Request::ALLGATHER, DataType::FLOAT32,
                          {2, 3})}, 0);
  n2.AddRequests({MakeReq("g", 1, Request::ALLGATHER, DataType::FLOAT32,
                          {5, 3})}, 0);
  Response g = n2.BuildResponse("g");
  CHECK(g.type == Response::ALLGATHER);
  CHECK(g.tensor_sizes == std::vector<int64_t>({2, 5}));
}

static void TestJoinReadiness() {
  Negotiator n(4);
  n.AddRequests({MakeReq("t", 0)}, 0);
  n.AddRequests({MakeReq("t", 1)}, 0);
  // ranks 2,3 joined: readiness threshold drops to 2
  auto ready = n.ReadyAfterJoin(2);
  CHECK(ready.size() == 1 && ready[0] == "t");
}

static void TestFusion() {
  auto mk = [](const std::string& name, int64_t elems,
               DataType dt = DataType::FLOAT32) {
    Response r;
    r.type = Response::ALLREDUCE;
    r.tensor_names = {name};
    r.tensor_sizes = {elems};
    r.dtype = dt;
    return r;
  };
  // threshold 100 floats = 400 bytes
  std::vector<Response> in = {mk("a", 50), mk("b", 40), mk("big", 200),
                              mk("c", 8), mk("d64", 10, DataType::FLOAT64)};
  auto out = Negotiator::Fuse(in, 400);
  // a+b+c fuse (50+40+8=98 floats); big alone; d64 alone (dtype differs)
  CHECK(out.size() == 3);
  CHECK(out[0].tensor_names.size() == 3);
  CHECK(out[0].tensor_names[2] == "c");
  CHECK(out[1].tensor_names[0] == "big");
  CHECK(out[2].dtype == DataType::FLOAT64);

  // broadcast never fuses
  Response bc;
  bc.type = Response::BROADCAST;
  bc.tensor_names = {"p"};
  bc.tensor_sizes = {10};
  auto out2 = Negotiator::Fuse({mk("x", 1), bc, mk("y", 1)}, 400);
  CHECK(out2.size() == 2);  // x+y fused via look-ahead, bc alone
}

static void TestResponseCache() {
  ResponseCache cache(2);
  Request q1 = MakeReq("a", 0);
  Response r1;
  r1.tensor_names = {"a"};
  CHECK(cache.Cached(q1) == ResponseCache::CacheState::MISS);
  cache.Put(q1, r1);
  CHECK(cache.Cached(q1) == ResponseCache::CacheState::HIT);
  // same name, different shape -> INVALID
  Request q1b = MakeReq("a", 0, Request::ALLREDUCE, DataType::FLOAT32,
                        {9});
  CHECK(cache.Cached(q1b) == ResponseCache::CacheState::INVALID);
  // LRU eviction at capacity 2
  cache.Put(MakeReq("b", 0), r1);
  cache.Get("a");  // touch a -> b is LRU
  cache.Put(MakeReq("c", 0), r1);
  CHECK(cache.Cached(MakeReq("b", 0)) == ResponseCache::CacheState::MISS);
  CHECK(cache.Cached(MakeReq("a", 0)) == ResponseCache::CacheState::HIT);
  // bit packing round-trip
  auto bits = cache.PackBits({"a", "c"});
  auto resps = cache.ResponsesForBits(bits);
  CHECK(resps.size() == 2);
}

static void TestStallInspector() {
  StallInspector si(0.0);  // warn immediately
  std::vector<std::pair<std::string, std::vector<int>>> pending = {
      {"slow", {0, 2}}};
  si.Check(pending, 4);
  // second check: age > 0 -> stalled
  si.Check(pending, 4);
  CHECK(si.stalled().size() == 1);
  CHECK(si.stalled()[0] == "slow");
}

static void TestReductionKernels() {
  float a[4] = {1, 2, 3, 4}, b[4] = {10, 20, 30, 40};
  ReduceInto(a, b, 4, DataType::FLOAT32, ReduceOp::SUM);
  CHECK(a[0] == 11 && a[3] == 44);
  ScaleInPlace(a, 4, DataType::FLOAT32, 0.5);
  CHECK(a[0] == 5.5f);
  int64_t ia[2] = {5, -3}, ib[2] = {2, 9};
  ReduceInto(ia, ib, 2, DataType::INT64, ReduceOp::MAX);
  CHECK(ia[0] == 5 && ia[1] == 9);
  // bf16: 1.0 + 2.0 = 3.0 exactly representable
  uint16_t ba[1] = {0x3f80}, bb[1] = {0x4000};
  ReduceInto(ba, bb, 1, DataType::BFLOAT16, ReduceOp::SUM);
  CHECK(ba[0] == 0x4040);
  // fp16 roundtrip through sum
  uint16_t ha[1] = {0x3c00}, hb[1] = {0x4000};  // 1.0, 2.0
  ReduceInto(ha, hb, 1, DataType::FLOAT16, ReduceOp::SUM);
  CHECK(ha[0] == 0x4200);  // 3.0
}

static void TestGaussianProcessEI() {
  // GP posterior should interpolate observations and EI should prefer
  // unexplored regions near the optimum over well-sampled poor ones.
  GaussianProcess gp(0.3, 1e-6);
  std::vector<std::vector<double>> xs = {{0.1}, {0.5}, {0.9}};
  std::vector<double> ys = {1.0, 5.0, 2.0};
  gp.Fit(xs, ys);
  double mean, var;
  gp.Predict({0.5}, mean, var);
  // normalized target: observed best maps to the top of the z-range
  CHECK(std::abs(mean - (5.0 - (8.0 / 3.0)) / std::sqrt(8.667 / 3.0)) < 0.2);
  CHECK(var < 0.1);
  double ei_near_best = gp.ExpectedImprovement({0.55});
  double ei_far_low = gp.ExpectedImprovement({0.1});
  CHECK(ei_near_best > ei_far_low);
}

static void TestParameterManagerConverges() {
  // Synthetic objective over (fusion, cycle): unimodal peak at
  // fusion = 2^22, cycle = 5ms. The tuner must finish its budget,
  // report the best-seen params, and write a parseable log.
  ParameterManager pm;
  ParameterManager::Options po;
  po.enabled = true;
  po.warmup_samples = 1;
  po.cycles_per_sample = 2;
  po.max_samples = 16;
  po.gp_noise = 1e-3;
  pm.Initialize(po, 64 << 20, 1.0, false, true);
  auto score = [](int64_t fusion, double cycle_ms) {
    double lf = std::log2(static_cast<double>(fusion));
    return 1e9 * std::exp(-0.1 * (lf - 22) * (lf - 22)) *
           std::exp(-0.05 * (cycle_ms - 5) * (cycle_ms - 5));
  };
  int guard = 0;
  while (pm.active() && ++guard < 10000) {
    // feed: bytes/elapsed == score at the currently proposed params
    double s = score(pm.fusion_threshold(), pm.cycle_time_ms());
    pm.Update(static_cast<int64_t>(s), 1.0);
  }
  CHECK(pm.done());
  CHECK(pm.samples() == po.max_samples);
  CHECK(pm.best_score() > 0);
  // converged params are the best observed sample
  CHECK(std::abs(score(pm.best_fusion_threshold(),
                       pm.best_cycle_time_ms()) -
                 pm.best_score()) < 1e-3 * pm.best_score());
  // current (adopted) params equal the best after convergence
  CHECK(pm.fusion_threshold() == pm.best_fusion_threshold());
  CHECK(pm.cycle_time_ms() == pm.best_cycle_time_ms());
  // categoricals were pinned (not tuned): never flipped off their init
  CHECK(pm.hierarchical() == false);
  CHECK(pm.cache_enabled() == true);
}

static void TestParameterManagerSampleAveraging() {
  // sample_repeats windows at the same proposal average into ONE
  // recorded sample — a lone bursty window must not become the score
  ParameterManager pm;
  ParameterManager::Options po;
  po.enabled = true;
  po.warmup_samples = 0;
  po.cycles_per_sample = 1;
  po.sample_repeats = 3;
  po.max_samples = 1;
  pm.Initialize(po, 64 << 20, 1.0, false, true);
  pm.Update(100, 1.0);
  CHECK(pm.samples() == 0);
  pm.Update(200, 1.0);
  CHECK(pm.samples() == 0);
  pm.Update(600, 1.0);
  CHECK(pm.samples() == 1);
  CHECK(std::abs(pm.best_score() - 300.0) < 1e-9);  // mean(100,200,600)
}

static void TestParameterManagerCategorical() {
  // Objective rewards hierarchical=on, cache=off 4x over any continuous
  // setting: the tuner must explore both values of each categorical dim
  // and converge on the winning combination (reference
  // parameter_manager.h:186-220 categorical grid).
  ParameterManager pm;
  ParameterManager::Options po;
  po.enabled = true;
  po.warmup_samples = 1;
  po.cycles_per_sample = 1;
  po.max_samples = 20;
  po.gp_noise = 1e-3;
  po.tune_hierarchical = true;
  po.tune_cache = true;
  pm.Initialize(po, 64 << 20, 1.0, false, true);
  bool saw_hier[2] = {false, false};
  bool saw_cache[2] = {false, false};
  int guard = 0;
  while (pm.active() && ++guard < 10000) {
    saw_hier[pm.hierarchical() ? 1 : 0] = true;
    saw_cache[pm.cache_enabled() ? 1 : 0] = true;
    double s = 1e8;
    if (pm.hierarchical()) s *= 2.0;
    if (!pm.cache_enabled()) s *= 2.0;
    pm.Update(static_cast<int64_t>(s), 1.0);
  }
  CHECK(pm.done());
  CHECK(saw_hier[0] && saw_hier[1]);
  CHECK(saw_cache[0] && saw_cache[1]);
  CHECK(pm.hierarchical() == true);
  CHECK(pm.cache_enabled() == false);
}

int main() {
  TestMessageRoundtrip();
  TestGaussianProcessEI();
  TestParameterManagerConverges();
  TestParameterManagerSampleAveraging();
  TestParameterManagerCategorical();
  TestNegotiatorReadiness();
  TestNegotiatorValidation();
  TestJoinReadiness();
  TestFusion();
  TestResponseCache();
  TestStallInspector();
  TestReductionKernels();
  if (failures == 0) {
    std::printf("ALL CXX UNIT TESTS PASSED\n");
    return 0;
  }
  std::fprintf(stderr, "%d failures\n", failures);
  return 1;
}
