// Host control plane: coordinator/worker negotiation over TCP.
//
// Role of the reference's abstract Controller (horovod/common/controller.h:
// 42-56) + its MPI/Gloo implementations (mpi_controller.cc,
// gloo_controller.cc): gather ready-tensor announcements to rank 0, let it
// decide what to execute, broadcast the decision, plus small-payload
// bcast/barrier/bit-allreduce used by the response cache and autotuner.
// Transport is plain TCP in a star (TPU VMs have no MPI); the bulk tensor
// path never goes through here.
#ifndef HVD_CONTROLLER_H
#define HVD_CONTROLLER_H

#include <atomic>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

#include "hvd/message.h"
#include "hvd/socket.h"

namespace hvd {

struct PeerInfo {
  std::string host;
  int data_port = 0;  // PeerMesh server port for bulk tensor traffic
};

// A rank's claimed host placement + requested hierarchical gates,
// piggybacked on the hello handshake. The coordinator validates that the
// claims form ONE consistent contiguous partition before any rank may run
// a hierarchical schedule — a per-rank env decision could split the job
// between the hierarchical and flat ring schedules and deadlock the data
// plane.
struct TopoClaim {
  int local_rank = 0, local_size = 1;
  int cross_rank = 0, cross_size = 1;
  uint8_t want_gates = 0;  // bit0: hierarchical allreduce, bit1: allgather
};

// agreed gates broadcast with the roster:
enum : uint8_t {
  kTopoCapable = 1,        // placement is a consistent 2-level partition
  kTopoHierAllreduce = 2,  // every rank requested + capable
  kTopoHierAllgather = 4,
};

class ControlPlane {
 public:
  // rank 0 listens on control_port; others connect to coord_host.
  ControlPlane(int rank, int size, std::string coord_host, int control_port);
  ~ControlPlane();

  // Exchange hellos; returns the full roster (host + data port per rank)
  // and the coordinator's agreed topology gates (kTopo* bits).
  // advertise_* describe this rank's PeerMesh endpoint.
  Status Initialize(const std::string& advertise_host, int advertise_port,
                    const TopoClaim& topo, std::vector<PeerInfo>& roster,
                    uint8_t& agreed_gates);

  int rank() const { return rank_; }
  int size() const { return size_; }
  bool is_coordinator() const { return rank_ == 0; }

  // --- synchronous round primitives (reference controller.h:44-56) ---
  // Worker side of a negotiation round: send requests, receive decision.
  Status SendReadyTensors(const RequestList& reqs);
  Status RecvFinalTensors(ResponseList& resp);
  // Coordinator side: receive all workers' requests, send the decision.
  Status RecvReadyTensors(std::vector<RequestList>& per_rank);
  Status SendFinalTensors(const ResponseList& resp);

  // Broadcast raw bytes from root to all (autotune params, roster, ...).
  Status Bcast(std::vector<uint8_t>& bytes, int root);
  Status Barrier();
  // Bitwise AND/OR allreduce over a packed bitvector (response cache sync,
  // reference controller.h:47-49 CrossRankBitwiseAnd/Or).
  Status BitAllreduce(std::vector<uint64_t>& bits, bool is_and);

  // Control-plane traffic accounting for the negotiation round methods
  // (the response-cache protocol exists to shrink these). Atomics: the
  // loop thread writes while user threads read.
  int64_t round_bytes_sent() const { return round_bytes_sent_.load(); }
  int64_t round_bytes_recv() const { return round_bytes_recv_.load(); }

 private:
  Status EnsureConnected();
  // gather variable-size frames from all ranks to rank 0
  Status GatherFrames(const std::vector<uint8_t>& mine,
                      std::vector<std::vector<uint8_t>>& all);
  Status BcastFrame(std::vector<uint8_t>& bytes, int root);

  int rank_;
  int size_;
  std::string coord_host_;
  int control_port_;
  std::unique_ptr<TcpServer> server_;                 // coordinator only
  std::vector<std::unique_ptr<TcpConnection>> workers_;  // coordinator only
  std::unique_ptr<TcpConnection> coord_;              // workers only
  std::mutex mu_;
  std::atomic<int64_t> round_bytes_sent_{0};
  std::atomic<int64_t> round_bytes_recv_{0};
};

}  // namespace hvd

#endif  // HVD_CONTROLLER_H
