// Pending-tensor table + request FIFO shared between the enqueue threads
// (Python callers) and the background cycle loop.
//
// Role of the reference's horovod/common/tensor_queue.{h,cc}: name-keyed
// entries, duplicate-name rejection, drain-on-shutdown. Entries own host
// buffers (input copied in at enqueue, output copied out at wait) — the
// core never aliases framework memory, which keeps the Python boundary a
// plain ctypes call.
#ifndef HVD_TENSOR_QUEUE_H
#define HVD_TENSOR_QUEUE_H

#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

#include "hvd/cpu_ops.h"
#include "hvd/message.h"

namespace hvd {

struct TensorTableEntry {
  std::string name;
  Request::Type type = Request::ALLREDUCE;
  DataType dtype = DataType::FLOAT32;
  TensorShape shape;
  int root_rank = -1;
  ReduceOp op = ReduceOp::SUM;
  double prescale = 1.0, postscale = 1.0;
  std::vector<uint8_t> data;    // input, reduced/gathered in place or grown
  // Borrowed caller buffer (zero-copy enqueue, the reference's
  // framework-tensor wrap, common.h:188-223): when set, ops read input
  // here and same-shape results (allreduce/adasum/broadcast) are written
  // back in place; `data` stays empty for those, so completion moves no
  // bytes. The caller guarantees the buffer outlives the op.
  uint8_t* ext = nullptr;
  int handle = -1;
};

class TensorQueue {
 public:
  // Returns DUPLICATE error if `name` is already pending (reference
  // common.h:160 DUPLICATE_NAME_ERROR).
  Status Add(TensorTableEntry entry, const Request& req);
  // Drain all pending requests for this cycle (reference
  // PopMessagesFromQueue).
  std::vector<Request> PopRequests();
  // Put a request back at the head of the FIFO (cache invalidation:
  // a tensor announced via the bitvector must renegotiate in full).
  void Requeue(const Request& req);
  // Remove and return the entry for a negotiated tensor.
  bool Take(const std::string& name, TensorTableEntry& out);
  // Names currently pending (for the stall inspector).
  std::vector<std::string> PendingNames();
  // Fail every pending entry (shutdown path); returns the entries so the
  // caller can complete their handles.
  std::vector<TensorTableEntry> DrainAll();

 private:
  std::mutex mu_;
  std::unordered_map<std::string, TensorTableEntry> table_;
  std::deque<Request> pending_;
};

}  // namespace hvd

#endif  // HVD_TENSOR_QUEUE_H
