// CPU collective implementations over the TCP peer mesh.
//
// Role of the reference's gloo/MPI op set (horovod/common/ops/
// gloo_operations.cc, mpi_operations.cc): the host data plane used by the
// eager API and the torch adapter when tensors live on host. TPU-resident
// data never comes through here — XLA emits those collectives
// (horovod_tpu/ops/collective.py).
//
// Allreduce is ring-based (bandwidth-optimal: 2(N-1)/N bytes per link),
// allgatherv is a ring rotation, broadcast is a star from root, Adasum is
// the recursive vector-halving distance-doubling algorithm with fp32
// dot/norm accumulation (reference: ops/adasum/adasum.h:186-330).
#ifndef HVD_CPU_OPS_H
#define HVD_CPU_OPS_H

#include <cstdint>
#include <vector>

#include "hvd/common.h"
#include "hvd/peer_mesh.h"

namespace hvd {

enum class ReduceOp : uint8_t { SUM = 0, AVERAGE = 1, MIN = 2, MAX = 3,
                                ADASUM = 4 };

// A subgroup of global ranks forming its own ring (intra-host ring,
// cross-host ring of chunk owners, ...). `members` lists global ranks in
// ring order; `pos` is this rank's index.
struct Group {
  std::vector<int> members;
  int pos = 0;
  int size() const { return static_cast<int>(members.size()); }
  int next() const { return members[(pos + 1) % size()]; }
  int prev() const { return members[(pos - 1 + size()) % size()]; }
};

// Process placement across hosts (reference: the LOCAL/CROSS communicator
// split that hierarchical NCCL/MPI ops ride, nccl_operations.cc:150,
// MPIHierarchicalAllgather). Ranks are contiguous per host, the hvdrun
// slot-allocation contract: rank = cross_rank * local_size + local_rank.
struct Topology {
  int rank = 0, size = 1;
  int local_rank = 0, local_size = 1;
  int cross_rank = 0, cross_size = 1;

  // True when the topology describes a real 2-level split this rank's
  // coordinates are consistent with.
  bool hierarchical() const {
    return local_size > 1 && cross_size > 1 &&
           local_size * cross_size == size &&
           rank == cross_rank * local_size + local_rank;
  }
  // Ranks on this host, ring-ordered.
  Group LocalGroup() const {
    Group grp;
    for (int i = 0; i < local_size; ++i)
      grp.members.push_back(cross_rank * local_size + i);
    grp.pos = local_rank;
    return grp;
  }
  // Same local_rank on every host, ring-ordered.
  Group CrossGroup() const {
    Group grp;
    for (int j = 0; j < cross_size; ++j)
      grp.members.push_back(j * local_size + local_rank);
    grp.pos = cross_rank;
    return grp;
  }
  // Host index a global rank lives on.
  int HostOf(int r) const { return local_size > 0 ? r / local_size : 0; }
};

// In-place elementwise reduce: acc[i] = op(acc[i], other[i]).
void ReduceInto(void* acc, const void* other, int64_t count, DataType dtype,
                ReduceOp op);

// In-place scale: data[i] *= factor (float types only; no-op otherwise).
void ScaleInPlace(void* data, int64_t count, DataType dtype, double factor);

// In-place ring allreduce over all ranks. AVERAGE divides by size at the
// end. count may be any value (chunks may be empty for tiny tensors).
Status RingAllreduce(PeerMesh& mesh, int rank, int size, void* data,
                     int64_t count, DataType dtype, ReduceOp op);

// Variable-size allgather: rank r contributes counts[r] elements; output
// holds the concatenation in rank order (reference MPI_Allgatherv).
// Ring reduce-scatter: the bandwidth-optimal first half of the ring
// allreduce, exposed as its own op (each rank sends ~1/N of allreduce's
// traffic and receives its `counts[rank]`-element slice of the reduction
// into `output`). `data` is clobbered as scratch.
Status RingReduceScatter(PeerMesh& mesh, int rank, int size, void* data,
                         const std::vector<int64_t>& counts, DataType dtype,
                         ReduceOp op, void* output);

Status RingAllgatherv(PeerMesh& mesh, int rank, int size, const void* input,
                      const std::vector<int64_t>& counts, DataType dtype,
                      void* output);

// Subgroup variants: the same ring schedules run over grp.members instead
// of ranks [0, size).
Status GroupRingAllreduce(PeerMesh& mesh, const Group& grp, void* data,
                          int64_t count, DataType dtype, ReduceOp op);
Status GroupRingReduceScatter(PeerMesh& mesh, const Group& grp, void* data,
                              const std::vector<int64_t>& counts,
                              DataType dtype, ReduceOp op, void* output);
Status GroupRingAllgatherv(PeerMesh& mesh, const Group& grp,
                           const void* input,
                           const std::vector<int64_t>& counts,
                           DataType dtype, void* output);
// Star broadcast from grp.members[root_pos] within the subgroup.
Status GroupBroadcast(PeerMesh& mesh, const Group& grp, void* data,
                      int64_t count, DataType dtype, int root_pos);

// 2-level allreduce (role of NCCLHierarchicalAllreduce,
// nccl_operations.cc:150-346): intra-host ring reduce-scatter, then each
// local rank runs the cross-host ring allreduce of its owned chunk (one
// concurrent stream per local rank), then intra-host ring allgather.
// Cross-host traffic per rank drops to ~2*count/local_size elements.
// AVERAGE divides by `average_denom` (callers pass the active-rank count).
Status HierarchicalAllreduce(PeerMesh& mesh, const Topology& topo,
                             void* data, int64_t count, DataType dtype,
                             ReduceOp op, int average_denom);

// 2-level allgatherv (role of MPIHierarchicalAllgather,
// mpi_operations.cc): intra-host allgatherv assembles each host's block,
// host leaders (local_rank 0) exchange whole host blocks cross-host, then
// the full result broadcasts intra-host. Only leaders move bytes across
// hosts. `counts` is per GLOBAL rank; output is the rank-order concat.
Status HierarchicalAllgatherv(PeerMesh& mesh, const Topology& topo,
                              const void* input,
                              const std::vector<int64_t>& counts,
                              DataType dtype, void* output);

// Star broadcast from root (in-place on non-roots).
Status Broadcast(PeerMesh& mesh, int rank, int size, void* data,
                 int64_t count, DataType dtype, int root);

// Pairwise-exchange all-to-all: input/output are size*block elements.
Status AllToAll(PeerMesh& mesh, int rank, int size, const void* input,
                int64_t block, DataType dtype, void* output);

// Adasum allreduce (power-of-2 size required, like the reference).
// Float dtypes only; dot/norm accumulation in fp64.
Status AdasumAllreduce(PeerMesh& mesh, ControlPlane& control, int rank,
                       int size, void* data, int64_t count, DataType dtype);

// 2-level Adasum (role of AdasumCudaAllreduceOp,
// adasum_cuda_operations.cc:96-260): intra-host ring reduce-scatter (sum)
// -> per-chunk Adasum across hosts (power-of-2 host count required) ->
// intra-host allgather -> divide by local_size (the reference's
// framework-layer divisor, torch/mpi_ops.py:104-110, folded in).
Status HierarchicalAdasumAllreduce(PeerMesh& mesh, const Topology& topo,
                                   void* data, int64_t count,
                                   DataType dtype);

}  // namespace hvd

#endif  // HVD_CPU_OPS_H
