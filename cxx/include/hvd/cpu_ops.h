// CPU collective implementations over the TCP peer mesh.
//
// Role of the reference's gloo/MPI op set (horovod/common/ops/
// gloo_operations.cc, mpi_operations.cc): the host data plane used by the
// eager API and the torch adapter when tensors live on host. TPU-resident
// data never comes through here — XLA emits those collectives
// (horovod_tpu/ops/collective.py).
//
// Allreduce is ring-based (bandwidth-optimal: 2(N-1)/N bytes per link),
// allgatherv is a ring rotation, broadcast is a star from root, Adasum is
// the recursive vector-halving distance-doubling algorithm with fp32
// dot/norm accumulation (reference: ops/adasum/adasum.h:186-330).
#ifndef HVD_CPU_OPS_H
#define HVD_CPU_OPS_H

#include <cstdint>
#include <vector>

#include "hvd/common.h"
#include "hvd/peer_mesh.h"

namespace hvd {

enum class ReduceOp : uint8_t { SUM = 0, AVERAGE = 1, MIN = 2, MAX = 3,
                                ADASUM = 4 };

// In-place elementwise reduce: acc[i] = op(acc[i], other[i]).
void ReduceInto(void* acc, const void* other, int64_t count, DataType dtype,
                ReduceOp op);

// In-place scale: data[i] *= factor (float types only; no-op otherwise).
void ScaleInPlace(void* data, int64_t count, DataType dtype, double factor);

// In-place ring allreduce over all ranks. AVERAGE divides by size at the
// end. count may be any value (chunks may be empty for tiny tensors).
Status RingAllreduce(PeerMesh& mesh, int rank, int size, void* data,
                     int64_t count, DataType dtype, ReduceOp op);

// Variable-size allgather: rank r contributes counts[r] elements; output
// holds the concatenation in rank order (reference MPI_Allgatherv).
// Ring reduce-scatter: the bandwidth-optimal first half of the ring
// allreduce, exposed as its own op (each rank sends ~1/N of allreduce's
// traffic and receives its `counts[rank]`-element slice of the reduction
// into `output`). `data` is clobbered as scratch.
Status RingReduceScatter(PeerMesh& mesh, int rank, int size, void* data,
                         const std::vector<int64_t>& counts, DataType dtype,
                         ReduceOp op, void* output);

Status RingAllgatherv(PeerMesh& mesh, int rank, int size, const void* input,
                      const std::vector<int64_t>& counts, DataType dtype,
                      void* output);

// Star broadcast from root (in-place on non-roots).
Status Broadcast(PeerMesh& mesh, int rank, int size, void* data,
                 int64_t count, DataType dtype, int root);

// Pairwise-exchange all-to-all: input/output are size*block elements.
Status AllToAll(PeerMesh& mesh, int rank, int size, const void* input,
                int64_t block, DataType dtype, void* output);

// Adasum allreduce (power-of-2 size required, like the reference).
// Float dtypes only; dot/norm accumulation in fp64.
Status AdasumAllreduce(PeerMesh& mesh, ControlPlane& control, int rank,
                       int size, void* data, int64_t count, DataType dtype);

}  // namespace hvd

#endif  // HVD_CPU_OPS_H
