// LRU cache of negotiated responses for steady-state cycles.
//
// Role of the reference's horovod/common/response_cache.{h,cc}: once a
// tensor's response has been negotiated, subsequent cycles skip the full
// request payload — ranks announce cache hits as a packed bitvector, the
// coordinator syncs bits with a bitwise-AND allreduce, and tensors whose
// bit survives on every rank proceed straight to execution
// (CacheCoordinator::sync, response_cache.h:107-167).
#ifndef HVD_RESPONSE_CACHE_H
#define HVD_RESPONSE_CACHE_H

#include <cstdint>
#include <list>
#include <string>
#include <unordered_map>
#include <vector>

#include "hvd/message.h"

namespace hvd {

class ResponseCache {
 public:
  enum class CacheState { MISS = 0, HIT = 1, INVALID = 2 };

  explicit ResponseCache(size_t capacity = 1024) : capacity_(capacity) {}

  // HIT when a response for this name is cached with identical parameters;
  // INVALID when cached with different shape/dtype/op (must renegotiate
  // and evict).
  CacheState Cached(const Request& req) const;
  // Insert/update. Returns the name evicted to make room ("" if none) —
  // callers tracking bit-announced tensors must requeue an evicted one.
  std::string Put(const Request& req, const Response& resp);
  const Response& Get(const std::string& name);
  uint32_t GetBit(const std::string& name) const;
  // Name currently holding `bit`, or "" if the bit is unassigned.
  std::string NameForBit(uint32_t bit) const;
  // Cached response type for a bit (ERROR if unassigned).
  Response::Type TypeForBit(uint32_t bit) const;
  void Erase(const std::string& name);
  size_t size() const { return entries_.size(); }
  size_t capacity() const { return capacity_; }

  // All cached responses whose bit is set in `bits`, in bit order.
  std::vector<Response> ResponsesForBits(
      const std::vector<uint64_t>& bits) const;
  // Pack the hit-bits for a set of names.
  std::vector<uint64_t> PackBits(const std::vector<std::string>& names) const;
  size_t NumBitWords() const { return (capacity_ + 63) / 64; }

 private:
  struct Entry {
    Response response;
    Request params;      // for validity checking
    uint32_t bit;        // stable bit position
    std::list<std::string>::iterator lru_it;
  };
  void Touch(const std::string& name);

  size_t capacity_;
  std::unordered_map<std::string, Entry> entries_;
  std::list<std::string> lru_;        // front = most recent
  std::vector<uint32_t> free_bits_;   // recycled bit positions
  uint32_t next_bit_ = 0;
  std::unordered_map<uint32_t, std::string> bit_to_name_;
};

}  // namespace hvd

#endif  // HVD_RESPONSE_CACHE_H
