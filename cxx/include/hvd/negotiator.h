// Coordinator-side negotiation: readiness counting, validation, fusion.
//
// Role of the reference's Controller::ComputeResponseList internals
// (horovod/common/controller.cc:55-346): IncrementTensorCount until every
// non-joined rank announced a tensor, validate cross-rank agreement
// (shape/dtype/op), then fuse compatible responses up to the fusion
// threshold (FuseResponses, controller.cc:639-769).
#ifndef HVD_NEGOTIATOR_H
#define HVD_NEGOTIATOR_H

#include <string>
#include <unordered_map>
#include <vector>

#include "hvd/message.h"

namespace hvd {

class Negotiator {
 public:
  explicit Negotiator(int size) : size_(size) {}

  // Feed one rank's announcements for this cycle. Returns the names that
  // just became ready (announced by all size - joined ranks).
  std::vector<std::string> AddRequests(const std::vector<Request>& reqs,
                                       int joined_count);
  // After joined_count changes (a rank joined), re-check readiness of
  // everything pending.
  std::vector<std::string> ReadyAfterJoin(int joined_count);

  // Build the (validated, possibly error) response for a ready tensor and
  // clear its state.
  Response BuildResponse(const std::string& name);

  // First rank's request for a pending tensor (cache key), or nullptr.
  const Request* FirstRequest(const std::string& name) const;
  // ALL ranks' requests for a pending tensor (cache validation needs
  // every rank's view, not just the first arrival's), or nullptr.
  const std::vector<Request>* Requests(const std::string& name) const;
  // Clear a tensor's state without building (cache-hit fast path).
  void Drop(const std::string& name);

  // Fuse compatible responses: same type, same dtype, no errors,
  // cumulative payload <= threshold bytes. Allreduce/Adasum only —
  // allgather/broadcast go out one-per-tensor. Order preserved with
  // look-ahead (a too-big tensor doesn't block later small ones from
  // fusing, reference controller.cc:687-696).
  static std::vector<Response> Fuse(std::vector<Response> responses,
                                    int64_t threshold_bytes);

  // Names currently waiting (for the stall inspector): name -> ranks that
  // have announced it.
  std::vector<std::pair<std::string, std::vector<int>>> Pending() const;

  bool has_pending() const { return !message_table_.empty(); }

 private:
  int size_;
  // name -> per-rank requests received so far (reference message_table_)
  std::unordered_map<std::string, std::vector<Request>> message_table_;
  std::vector<std::string> arrival_order_;
};

}  // namespace hvd

#endif  // HVD_NEGOTIATOR_H
