// Coordinator-side stall watchdog.
//
// Role of the reference's horovod/common/stall_inspector.{h,cc}: warn when
// a tensor has been announced by some ranks but is still missing on others
// for longer than the warning threshold (default 60 s), listing the
// missing ranks; optionally abort the job after a shutdown threshold
// (HOROVOD_STALL_SHUTDOWN_TIME_SECONDS).
#ifndef HVD_STALL_INSPECTOR_H
#define HVD_STALL_INSPECTOR_H

#include <chrono>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

namespace hvd {

class StallInspector {
 public:
  StallInspector(double warn_sec = 60.0, double shutdown_sec = 0.0)
      : warn_sec_(warn_sec), shutdown_sec_(shutdown_sec) {}

  // Feed the currently-pending negotiation state
  // (name -> ranks that have announced). Returns true if the shutdown
  // threshold was crossed. Warnings are printed to stderr.
  bool Check(
      const std::vector<std::pair<std::string, std::vector<int>>>& pending,
      int world_size);
  // Names that have been warned about (tested directly).
  const std::vector<std::string>& stalled() const { return stalled_; }

 private:
  double warn_sec_;
  double shutdown_sec_;
  std::unordered_map<std::string, std::chrono::steady_clock::time_point>
      first_seen_;
  std::chrono::steady_clock::time_point last_warn_{};
  std::vector<std::string> stalled_;
};

}  // namespace hvd

#endif  // HVD_STALL_INSPECTOR_H
