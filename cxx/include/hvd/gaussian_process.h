// Gaussian-process regression + expected-improvement acquisition.
//
// Role of the reference's horovod/common/optim/{gaussian_process,
// bayesian_optimization}.cc — re-implemented without Eigen/LBFGS: an RBF
// kernel with fixed length-scale over normalized [0,1]^d inputs, Cholesky
// solve, and EI maximized over random candidates. Sufficient for the 2-D
// (fusion threshold x cycle time) tuning space.
#ifndef HVD_GAUSSIAN_PROCESS_H
#define HVD_GAUSSIAN_PROCESS_H

#include <cstdint>
#include <vector>

namespace hvd {

class GaussianProcess {
 public:
  explicit GaussianProcess(double length_scale = 0.3,
                           double noise = 1e-4)
      : length_scale_(length_scale), noise_(noise) {}

  // Fit on observations (x in [0,1]^d, y arbitrary scale; y is z-score
  // normalized internally).
  void Fit(const std::vector<std::vector<double>>& xs,
           const std::vector<double>& ys);
  // Posterior mean and variance (of the normalized target) at x.
  void Predict(const std::vector<double>& x, double& mean,
               double& var) const;
  // Expected improvement over the best observed y (maximization).
  double ExpectedImprovement(const std::vector<double>& x,
                             double xi = 0.01) const;
  bool fitted() const { return !xs_.empty(); }

 private:
  double Kernel(const std::vector<double>& a,
                const std::vector<double>& b) const;

  double length_scale_;
  double noise_;
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_norm_;
  double y_mean_ = 0, y_std_ = 1;
  double best_norm_ = 0;
  std::vector<std::vector<double>> chol_;  // lower-triangular L
  std::vector<double> alpha_;              // K^-1 y
};

}  // namespace hvd

#endif  // HVD_GAUSSIAN_PROCESS_H
