// Common types for the native host-side core.
//
// TPU-native rebuild of the reference's horovod/common/common.h:104-260
// (Status, DataType, TensorShape) — re-designed, not translated: no
// framework Tensor virtual interface (the TPU data plane is compiled by
// XLA; this core only ever owns host CPU buffers), no CUDA events.
#ifndef HVD_COMMON_H
#define HVD_COMMON_H

#include <cstdint>
#include <numeric>
#include <string>
#include <vector>

namespace hvd {

enum class StatusType : uint8_t {
  OK = 0,
  UNKNOWN_ERROR = 1,
  PRECONDITION_ERROR = 2,
  ABORTED = 3,
  INVALID_ARGUMENT = 4,
  IN_PROGRESS = 5,
};

class Status {
 public:
  Status() = default;
  static Status OK() { return Status(); }
  static Status Error(StatusType t, std::string msg) {
    Status s; s.type_ = t; s.reason_ = std::move(msg); return s;
  }
  static Status Unknown(std::string msg) {
    return Error(StatusType::UNKNOWN_ERROR, std::move(msg));
  }
  static Status Precondition(std::string msg) {
    return Error(StatusType::PRECONDITION_ERROR, std::move(msg));
  }
  static Status Aborted(std::string msg) {
    return Error(StatusType::ABORTED, std::move(msg));
  }
  static Status InvalidArgument(std::string msg) {
    return Error(StatusType::INVALID_ARGUMENT, std::move(msg));
  }
  static Status InProgress() {
    Status s; s.type_ = StatusType::IN_PROGRESS; return s;
  }
  bool ok() const { return type_ == StatusType::OK; }
  bool in_progress() const { return type_ == StatusType::IN_PROGRESS; }
  StatusType type() const { return type_; }
  const std::string& reason() const { return reason_; }

 private:
  StatusType type_ = StatusType::OK;
  std::string reason_;
};

// Wire dtypes (reference: message.h:27-39, 11 dtypes). BFLOAT16 added —
// it is the TPU wire format of choice.
enum class DataType : uint8_t {
  UINT8 = 0, INT8 = 1, UINT16 = 2, INT16 = 3,
  INT32 = 4, INT64 = 5, FLOAT16 = 6, FLOAT32 = 7,
  FLOAT64 = 8, BOOL = 9, BFLOAT16 = 10,
};

inline size_t DataTypeSize(DataType dt) {
  switch (dt) {
    case DataType::UINT8: case DataType::INT8: case DataType::BOOL:
      return 1;
    case DataType::UINT16: case DataType::INT16: case DataType::FLOAT16:
    case DataType::BFLOAT16:
      return 2;
    case DataType::INT32: case DataType::FLOAT32:
      return 4;
    case DataType::INT64: case DataType::FLOAT64:
      return 8;
  }
  return 0;
}

const char* DataTypeName(DataType dt);

class TensorShape {
 public:
  TensorShape() = default;
  explicit TensorShape(std::vector<int64_t> dims) : dims_(std::move(dims)) {}
  void AddDim(int64_t d) { dims_.push_back(d); }
  int ndim() const { return static_cast<int>(dims_.size()); }
  int64_t dim(int i) const { return dims_[i]; }
  const std::vector<int64_t>& dims() const { return dims_; }
  int64_t num_elements() const {
    return std::accumulate(dims_.begin(), dims_.end(), int64_t{1},
                           std::multiplies<int64_t>());
  }
  bool operator==(const TensorShape& o) const { return dims_ == o.dims_; }
  bool operator!=(const TensorShape& o) const { return dims_ != o.dims_; }
  std::string DebugString() const;

 private:
  std::vector<int64_t> dims_;
};

// Env helpers (reference: utils/env_parser.cc).
int64_t EnvInt(const char* name, int64_t dflt);
double EnvDouble(const char* name, double dflt);
std::string EnvStr(const char* name, const std::string& dflt);
bool EnvBool(const char* name, bool dflt);

}  // namespace hvd

#endif  // HVD_COMMON_H
