// Global state, background negotiation/execution loop, and the C API.
//
// Role of the reference's horovod/common/operations.cc: the singleton
// HorovodGlobalState, InitializeHorovodOnce spawning the background
// thread, RunLoopOnce per-cycle negotiation + execution, and the
// extern "C" surface Python binds via ctypes (operations.cc:641-778 and
// the Enqueue* functions 782-931).
//
// TPU adaptation: this core is the HOST data plane (eager numpy/torch
// tensors, control utilities, Join) — collectives on TPU-resident arrays
// are compiled by XLA and never enter this queue.
#ifndef HVD_OPERATIONS_H
#define HVD_OPERATIONS_H

#include <cstdint>

extern "C" {

// Lifecycle. Returns 0 on success.
int hvdc_init(int rank, int size, const char* coord_host, int coord_port,
              const char* advertise_host);
int hvdc_shutdown();
int hvdc_is_initialized();
int hvdc_rank();
int hvdc_size();

// Enqueue a collective; returns a handle (>=0) or -1 on immediate error
// (error text via hvdc_last_error). `type` is Request::Type, `op` is
// ReduceOp, `dtype` is DataType.
int hvdc_enqueue(int type, const char* name, const void* data,
                 const int64_t* shape, int ndim, int dtype, int op,
                 int root_rank, double prescale, double postscale);
// Zero-copy variant: the core borrows `data` (no copy-in); for
// allreduce/adasum/broadcast the result is written back into `data`
// in place (no copy-out — hvdc_output_size reports 0). The caller must
// keep the buffer alive and unmodified until the handle completes.
// Reduce-scatter clobbers the buffer as ring scratch.
// Failure contract: if the collective fails, the borrowed buffer is
// UNDEFINED — the single-tensor fast path reduces in place (partial
// results may be visible), while the fused path leaves it untouched;
// which path a tensor takes depends on what fused that cycle, so
// callers must treat the data as lost on any non-ok handle status.
int hvdc_enqueue_borrow(int type, const char* name, void* data,
                        const int64_t* shape, int ndim, int dtype, int op,
                        int root_rank, double prescale, double postscale);
// Cumulative host-side memcpy bytes (enqueue copy-in, fusion staging,
// output copy-out) — zero-copy paths exist to keep this flat.
int64_t hvdc_copy_bytes();
int hvdc_enqueue_join();

// 0 = pending, 1 = done ok, -1 = done with error.
int hvdc_poll(int handle);
int hvdc_wait(int handle);
const char* hvdc_error_message(int handle);
const char* hvdc_last_error();
int64_t hvdc_output_size(int handle);
int hvdc_copy_output(int handle, void* dst);
void hvdc_release(int handle);

// Convenience: negotiated barrier across all ranks (blocking).
int hvdc_barrier();

// Autotuner introspection: current (possibly tuned) fusion threshold,
// cycle time, and the categorical hierarchical-allreduce / cache gates,
// plus coordinator-side sample count / convergence flag (workers report
// samples=-1). Returns 1 when HOROVOD_AUTOTUNE is on, 0 when off, -1
// when the core is not initialized.
int hvdc_autotune_state(int64_t* fusion_threshold, double* cycle_time_ms,
                        int* samples, int* done, int* hierarchical,
                        int* cache_enabled);

// Cumulative control-plane bytes this rank has sent/received in
// negotiation rounds (the response-cache bitvector protocol exists to
// shrink these in steady state). Returns 0 on success.
int hvdc_control_bytes(int64_t* sent, int64_t* recvd);

// Cumulative data-plane payload bytes this rank has sent to peers on the
// same host vs other hosts (per the HOROVOD_LOCAL_*/CROSS_* topology) —
// the evidence hierarchical collectives cut cross-host traffic. Returns
// 0 on success.
int hvdc_data_bytes(int64_t* local_bytes, int64_t* cross_bytes);

}  // extern "C"

#endif  // HVD_OPERATIONS_H
