// Leveled, env-controlled logging for the host core.
// Rebuilds the role of the reference's common/logging.{h,cc} (LOG(level)
// stream macro, HOROVOD_LOG_LEVEL / HOROVOD_LOG_TIMESTAMP env control,
// rank prefix) as a header-only utility: the hot paths must be able to
// compile the call away when the level is off, and the negotiation loop
// must never block on stderr — messages are single write()s.
#pragma once

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <sstream>
#include <string>
#include <strings.h>  // strcasecmp lives in POSIX <strings.h>, not
                      // <cstring>; relying on glibc's transitive
                      // include breaks stricter libcs

namespace hvd {
namespace logging {

enum class Level : int { TRACE = 0, DEBUG, INFO, WARNING, ERROR, FATAL };

inline const char* LevelName(Level l) {
  switch (l) {
    case Level::TRACE: return "trace";
    case Level::DEBUG: return "debug";
    case Level::INFO: return "info";
    case Level::WARNING: return "warning";
    case Level::ERROR: return "error";
    case Level::FATAL: return "fatal";
  }
  return "?";
}

inline Level ParseLevel(const char* s) {
  if (!s) return Level::WARNING;
  if (!strcasecmp(s, "trace")) return Level::TRACE;
  if (!strcasecmp(s, "debug")) return Level::DEBUG;
  if (!strcasecmp(s, "info")) return Level::INFO;
  if (!strcasecmp(s, "warning") || !strcasecmp(s, "warn"))
    return Level::WARNING;
  if (!strcasecmp(s, "error")) return Level::ERROR;
  if (!strcasecmp(s, "fatal")) return Level::FATAL;
  return Level::WARNING;
}

struct Config {
  std::atomic<int> min_level{
      static_cast<int>(ParseLevel(std::getenv("HOROVOD_LOG_LEVEL")))};
  std::atomic<bool> timestamp{[] {
    const char* t = std::getenv("HOROVOD_LOG_TIMESTAMP");
    return t != nullptr && strcmp(t, "0") != 0;
  }()};
  std::atomic<int> rank{-1};  // set by operations.cc at init
};

inline Config& config() {
  static Config c;
  return c;
}

inline bool Enabled(Level l) {
  return static_cast<int>(l) >= config().min_level.load();
}

// One-shot message builder: formats into a local buffer, emits a single
// fwrite so concurrent threads' lines do not interleave.
class Message {
 public:
  explicit Message(Level level, const char* file, int line)
      : level_(level) {
    if (config().timestamp.load()) {
      char buf[32];
      time_t now = time(nullptr);
      struct tm tmv;
      localtime_r(&now, &tmv);
      strftime(buf, sizeof(buf), "%F %T", &tmv);
      os_ << "[" << buf << "] ";
    }
    os_ << "[" << LevelName(level) << "]";
    int r = config().rank.load();
    if (r >= 0) os_ << "[rank " << r << "]";
    os_ << " ";
    const char* base = strrchr(file, '/');
    os_ << (base ? base + 1 : file) << ":" << line << ": ";
  }

  template <typename T>
  Message& operator<<(const T& v) {
    os_ << v;
    return *this;
  }

  ~Message() {
    os_ << "\n";
    std::string s = os_.str();
    fwrite(s.data(), 1, s.size(), stderr);
    if (level_ == Level::FATAL) abort();
  }

 private:
  Level level_;
  std::ostringstream os_;
};

}  // namespace logging
}  // namespace hvd

// Usage: HVD_LOG(INFO) << "controller up on " << port;
// The condition short-circuits before any formatting when the level is
// disabled, so TRACE/DEBUG in the cycle loop cost one atomic load.
#define HVD_LOG(level)                                                   \
  if (!hvd::logging::Enabled(hvd::logging::Level::level)) {              \
  } else                                                                 \
    hvd::logging::Message(hvd::logging::Level::level, __FILE__, __LINE__)
