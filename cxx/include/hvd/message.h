// Control-plane messages and their wire format.
//
// Role of the reference's horovod/common/message.h:46-221 (Request /
// Response / RequestList / ResponseList) — but serialized with a small
// hand-rolled length-prefixed binary codec instead of FlatBuffers (zero
// third-party deps; messages are tiny and host-side only).
#ifndef HVD_MESSAGE_H
#define HVD_MESSAGE_H

#include <cstdint>
#include <string>
#include <vector>

#include "hvd/common.h"

namespace hvd {

// Binary writer/reader for the wire format. All integers little-endian.
class Writer {
 public:
  void u8(uint8_t v) { buf_.push_back(v); }
  void i32(int32_t v) { append(&v, 4); }
  void i64(int64_t v) { append(&v, 8); }
  void str(const std::string& s) {
    i32(static_cast<int32_t>(s.size()));
    buf_.insert(buf_.end(), s.begin(), s.end());
  }
  void bytes(const std::vector<uint8_t>& b) {
    i32(static_cast<int32_t>(b.size()));
    buf_.insert(buf_.end(), b.begin(), b.end());
  }
  const std::vector<uint8_t>& data() const { return buf_; }
  std::vector<uint8_t> take() { return std::move(buf_); }

 private:
  void append(const void* p, size_t n) {
    const uint8_t* b = static_cast<const uint8_t*>(p);
    buf_.insert(buf_.end(), b, b + n);
  }
  std::vector<uint8_t> buf_;
};

class Reader {
 public:
  Reader(const uint8_t* data, size_t len) : p_(data), end_(data + len) {}
  explicit Reader(const std::vector<uint8_t>& b)
      : Reader(b.data(), b.size()) {}
  uint8_t u8() { return *p_++; }
  int32_t i32() { int32_t v; copy(&v, 4); return v; }
  int64_t i64() { int64_t v; copy(&v, 8); return v; }
  std::string str() {
    int32_t n = i32();
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }
  std::vector<uint8_t> bytes() {
    int32_t n = i32();
    std::vector<uint8_t> b(p_, p_ + n);
    p_ += n;
    return b;
  }
  bool done() const { return p_ >= end_; }

 private:
  void copy(void* dst, size_t n) {
    std::copy(p_, p_ + n, static_cast<uint8_t*>(dst));
    p_ += n;
  }
  const uint8_t* p_;
  const uint8_t* end_;
};

// A worker's announcement that a tensor is ready (reference:
// message.h:46-99).
struct Request {
  enum Type : uint8_t {
    ALLREDUCE = 0, ALLGATHER = 1, BROADCAST = 2, JOIN = 3, ADASUM = 4,
    ALLTOALL = 5, REDUCESCATTER = 6, BARRIER = 7,
  };
  Type type = ALLREDUCE;
  int32_t request_rank = 0;
  DataType dtype = DataType::FLOAT32;
  std::string tensor_name;
  int32_t root_rank = -1;
  TensorShape shape;
  double prescale_factor = 1.0;
  double postscale_factor = 1.0;
  uint8_t reduce_op = 0;  // ReduceOp; must agree across ranks

  void Serialize(Writer& w) const;
  static Request Deserialize(Reader& r);
};

const char* RequestTypeName(Request::Type t);

// The coordinator's instruction of what to execute (reference:
// message.h:131-191). A fused response carries several tensor names.
struct Response {
  enum Type : uint8_t {
    ALLREDUCE = 0, ALLGATHER = 1, BROADCAST = 2, JOIN = 3, ADASUM = 4,
    ALLTOALL = 5, REDUCESCATTER = 6, BARRIER = 7, ERROR = 8,
  };
  Type type = ALLREDUCE;
  std::vector<std::string> tensor_names;
  std::string error_message;
  // ALLREDUCE/ADASUM: per-tensor element counts (zero-fill for joined
  // ranks + fusion planning). ALLGATHER: per-rank first-dim sizes
  // (reference tensor_sizes).
  std::vector<int64_t> tensor_sizes;
  DataType dtype = DataType::FLOAT32;
  uint8_t reduce_op = 0;  // ReduceOp for ALLREDUCE responses
  // ranks contributing real data (size - joined); the AVERAGE divisor must
  // be identical on every rank, so the coordinator pins it here
  int32_t active_ranks = 0;

  void Serialize(Writer& w) const;
  static Response Deserialize(Reader& r);
};

struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;
  // steady-state announcement: packed bit per cache position this rank
  // has ready with cache-identical parameters (reference
  // response_cache.h:107-167 CacheCoordinator bits). Tensors announced
  // here do NOT appear in `requests` — that is the bytes saving.
  std::vector<uint64_t> cache_bits;

  std::vector<uint8_t> Serialize() const;
  static RequestList Deserialize(const std::vector<uint8_t>& buf);
};

struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
  // autotune piggyback (reference: Controller::SynchronizeParameters) —
  // when set, workers adopt these tuned values for the next cycles
  bool has_tuned_params = false;
  int64_t tuned_fusion_threshold = 0;
  double tuned_cycle_time_ms = 0;  // serialized bit-exactly
  // categorical tuning decisions: every rank must run the same collective
  // schedule and cache protocol in the same cycle
  uint8_t tuned_hierarchical = 0;
  uint8_t tuned_cache = 1;

  // steady-state decision: bit positions every (non-joined) rank
  // announced as cache hits — each rank reconstructs those responses
  // from its local cache replica instead of receiving them in
  // `responses`. `cache_invalid` orders an eviction (a rank's params
  // changed); evicted tensors renegotiate via the full path.
  std::vector<uint64_t> cache_hits;
  std::vector<uint32_t> cache_invalid;
  // AVERAGE divisor for reconstructed cache-hit responses (size - joined)
  int32_t active_ranks = 0;

  std::vector<uint8_t> Serialize() const;
  static ResponseList Deserialize(const std::vector<uint8_t>& buf);
};

}  // namespace hvd

#endif  // HVD_MESSAGE_H
