// Minimal TCP framing layer for the host control/data planes.
//
// Plays the role the vendored gloo TCP transport + HTTPRequest library play
// in the reference (horovod/common/gloo/, third_party/) — TPU VMs have no
// MPI, so everything host-side rides plain TCP. Frames are
// [uint32 little-endian length][payload].
#ifndef HVD_SOCKET_H
#define HVD_SOCKET_H

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "hvd/common.h"

namespace hvd {

class TcpConnection {
 public:
  explicit TcpConnection(int fd) : fd_(fd) {}
  ~TcpConnection();
  TcpConnection(const TcpConnection&) = delete;
  TcpConnection& operator=(const TcpConnection&) = delete;

  // Connect with retry (the peer may not be listening yet during startup).
  static std::unique_ptr<TcpConnection> Connect(const std::string& host,
                                                int port,
                                                double timeout_sec = 60.0);

  Status SendFrame(const void* data, uint32_t len);
  Status SendFrame(const std::vector<uint8_t>& buf) {
    return SendFrame(buf.data(), static_cast<uint32_t>(buf.size()));
  }
  Status RecvFrame(std::vector<uint8_t>& out);
  // Frame receive with a whole-frame absolute deadline and a length cap
  // (for pre-authentication handshakes: a silent, dripping, or hostile
  // peer must not block the caller or force a huge allocation).
  Status RecvFrameDeadline(std::vector<uint8_t>& out, double timeout_sec,
                           uint32_t max_len = 1 << 16);
  // Raw (unframed) IO for bulk tensor payloads.
  Status SendRaw(const void* data, size_t len);
  Status RecvRaw(void* data, size_t len);
  // Switch to non-blocking mode (required before use with the data-plane
  // Progress engine; SendRaw/RecvRaw keep working — they poll on EAGAIN).
  void SetNonBlocking();
  int fd() const { return fd_; }

 private:
  int fd_;
};

class TcpServer {
 public:
  // Binds and listens on port (0 = ephemeral). Check port() after.
  explicit TcpServer(int port);
  ~TcpServer();
  TcpServer(const TcpServer&) = delete;
  TcpServer& operator=(const TcpServer&) = delete;

  std::unique_ptr<TcpConnection> Accept(double timeout_sec = 60.0);
  int port() const { return port_; }
  bool ok() const { return fd_ >= 0; }

 private:
  int fd_ = -1;
  int port_ = 0;
};

}  // namespace hvd

#endif  // HVD_SOCKET_H
