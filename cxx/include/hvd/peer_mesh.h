// Bulk data-plane p2p connections between ranks.
//
// Role of the vendored gloo full-mesh TCP transport in the reference
// (horovod/common/gloo/gloo_context.cc:30-56 full-mesh rendezvous;
// gloo_operations.cc collectives ride it). Connections are lazy: the lower
// rank initiates, the higher rank accepts (a background accept thread
// registers inbound peers). All transfers go through a poll()-based
// progress engine so simultaneous send/recv pairs (ring steps, pairwise
// exchanges) cannot deadlock on full TCP buffers — the role MPI_Sendrecv
// plays in the reference's Adasum path (adasum_mpi.cc).
#ifndef HVD_PEER_MESH_H
#define HVD_PEER_MESH_H

#include <atomic>
#include <condition_variable>
#include <map>
#include <memory>
#include <mutex>
#include <thread>
#include <vector>

#include "hvd/controller.h"
#include "hvd/socket.h"

namespace hvd {

// One pending raw transfer for the progress engine.
struct Transfer {
  int fd = -1;
  bool is_send = false;
  const uint8_t* send_buf = nullptr;
  uint8_t* recv_buf = nullptr;
  size_t len = 0;
  size_t done = 0;
};

// Drive all transfers to completion concurrently (poll loop).
Status Progress(std::vector<Transfer>& transfers);

class PeerMesh {
 public:
  PeerMesh(int rank, int size);
  ~PeerMesh();

  Status Start();             // bind server + start accept thread
  int port() const;
  void SetRoster(std::vector<PeerInfo> roster);

  // Get (or establish) the duplex connection to peer.
  Status Get(int peer, TcpConnection** out);

  // Blocking helpers (all full-duplex-safe via Progress).
  Status SendTo(int peer, const void* data, size_t len);
  Status RecvFrom(int peer, void* data, size_t len);
  Status SendRecv(int peer, const void* send, size_t send_len, void* recv,
                  size_t recv_len);
  // Simultaneous ring step: send to `next`, receive from `prev`.
  Status RingStep(int next, int prev, const void* send, size_t send_len,
                  void* recv, size_t recv_len);

  // Cumulative payload bytes sent to `peer` (hierarchical-collective
  // traffic accounting; the reference's NCCL layer has no equivalent
  // introspection — this exists so tests can prove the intra/cross-host
  // traffic split).
  int64_t bytes_sent_to(int peer) const;

  void Shutdown();

 private:
  void AcceptLoop();

  int rank_;
  int size_;
  std::unique_ptr<TcpServer> server_;
  std::vector<PeerInfo> roster_;
  std::map<int, std::unique_ptr<TcpConnection>> conns_;
  std::unique_ptr<std::atomic<int64_t>[]> sent_bytes_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::thread accept_thread_;
  bool shutdown_ = false;
};

}  // namespace hvd

#endif  // HVD_PEER_MESH_H
