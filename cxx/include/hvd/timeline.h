// Chrome-tracing timeline writer.
//
// Role of the reference's horovod/common/timeline.{h,cc}: per-tensor
// phase events (NEGOTIATE -> op -> nested activities) written as
// chrome://tracing JSON by a dedicated writer thread so the hot path only
// pays an enqueue. Enabled by HOROVOD_TIMELINE=<path>.
#ifndef HVD_TIMELINE_H
#define HVD_TIMELINE_H

#include <chrono>
#include <condition_variable>
#include <deque>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>

namespace hvd {

class Timeline {
 public:
  ~Timeline();
  void Initialize(const std::string& path, int rank);
  bool Initialized() const { return initialized_; }

  // phase markers; category shows as the chrome trace "cat"
  void NegotiateStart(const std::string& name, const std::string& op);
  void NegotiateEnd(const std::string& name);
  void Start(const std::string& name, const std::string& op);
  void ActivityStart(const std::string& name, const std::string& activity);
  void ActivityEnd(const std::string& name);
  void End(const std::string& name);
  void MarkCycle();  // HOROVOD_TIMELINE_MARK_CYCLES

  void Shutdown();

 private:
  struct Event {
    char phase;  // 'B' begin, 'E' end, 'i' instant
    std::string tid;  // per-tensor lane
    std::string label;
    int64_t ts_us;
  };
  void Enqueue(Event e);
  void WriterLoop();
  int64_t NowUs() const;

  bool initialized_ = false;
  int rank_ = 0;
  std::ofstream file_;
  std::deque<Event> queue_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::thread writer_;
  bool shutdown_ = false;
  bool first_event_ = true;
  std::chrono::steady_clock::time_point start_;
};

}  // namespace hvd

#endif  // HVD_TIMELINE_H
