// Autotuner: online Bayesian optimization of the fusion threshold and
// cycle time.
//
// Role of the reference's horovod/common/parameter_manager.{h,cc}: score
// each sample window as bytes/sec of allreduced payload, discard warmup
// windows, propose the next (fusion_threshold, cycle_time) via GP expected
// improvement, and converge on the best after a sample budget. The
// coordinator runs it; tuned values ride to workers in the ResponseList
// (reference: Controller::SynchronizeParameters).
#ifndef HVD_PARAMETER_MANAGER_H
#define HVD_PARAMETER_MANAGER_H

#include <cstdint>
#include <fstream>
#include <string>
#include <vector>

#include "hvd/gaussian_process.h"

namespace hvd {

class ParameterManager {
 public:
  struct Options {
    bool enabled = false;
    int warmup_samples = 3;
    int cycles_per_sample = 50;
    int max_samples = 20;
    double gp_noise = 1e-3;
    std::string log_file;
    uint64_t seed = 12345;
  };

  void Initialize(const Options& opts, int64_t fusion_threshold,
                  double cycle_time_ms);
  bool active() const { return opts_.enabled && !done_; }
  bool enabled() const { return opts_.enabled; }
  bool done() const { return done_; }

  // Record one background cycle's processed payload. Returns true when the
  // tuned parameters changed (caller re-broadcasts them).
  bool Update(int64_t bytes, double elapsed_sec);

  int64_t fusion_threshold() const { return current_fusion_; }
  double cycle_time_ms() const { return current_cycle_ms_; }
  int64_t best_fusion_threshold() const { return best_fusion_; }
  double best_cycle_time_ms() const { return best_cycle_ms_; }
  double best_score() const { return best_score_; }
  int samples() const { return static_cast<int>(ys_.size()); }

 private:
  void Propose();
  double NextRand();

  Options opts_;
  bool done_ = false;
  int cycles_ = 0;
  int64_t bytes_acc_ = 0;
  double time_acc_ = 0;
  int warmup_left_ = 0;

  // normalized [0,1]^2 coords: x0 = log2(fusion)/26, x1 = cycle/25
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  GaussianProcess gp_;

  int64_t current_fusion_ = 64 << 20;
  double current_cycle_ms_ = 1.0;
  int64_t best_fusion_ = 64 << 20;
  double best_cycle_ms_ = 1.0;
  double best_score_ = -1;
  uint64_t rng_state_ = 12345;
  std::ofstream log_;
};

}  // namespace hvd

#endif  // HVD_PARAMETER_MANAGER_H
