// Autotuner: online Bayesian optimization of the fusion threshold and
// cycle time, plus the categorical hierarchical-allreduce and
// response-cache gates.
//
// Role of the reference's horovod/common/parameter_manager.{h,cc}: score
// each sample window as bytes/sec of payload moved, discard warmup
// windows, propose the next parameter set via GP expected improvement
// (categoricals ride the GP as 0/1 coordinates; the random phase cycles
// every category combination the way the reference's
// CategoricalParameterChunk walks its grid, parameter_manager.h:186-220),
// and converge on the best after a sample budget. The coordinator runs
// it; tuned values ride to workers in the ResponseList (reference:
// Controller::SynchronizeParameters).
#ifndef HVD_PARAMETER_MANAGER_H
#define HVD_PARAMETER_MANAGER_H

#include <cstdint>
#include <fstream>
#include <string>
#include <vector>

#include "hvd/gaussian_process.h"

namespace hvd {

class ParameterManager {
 public:
  struct Options {
    bool enabled = false;
    int warmup_samples = 3;
    int cycles_per_sample = 50;
    // windows measured (and averaged) per proposal before the score is
    // recorded — bursty enqueue patterns alias into a single window, so
    // one window per config is a noisy objective for the GP
    int sample_repeats = 2;
    int max_samples = 20;
    double gp_noise = 1e-3;
    std::string log_file;
    uint64_t seed = 12345;
    // categorical dims join the search only when the deployment can
    // exercise them (a real multi-host topology / a cache at all)
    bool tune_hierarchical = false;
    bool tune_cache = false;
  };

  void Initialize(const Options& opts, int64_t fusion_threshold,
                  double cycle_time_ms, bool hierarchical,
                  bool cache_enabled);
  bool active() const { return opts_.enabled && !done_; }
  bool enabled() const { return opts_.enabled; }
  bool done() const { return done_; }

  // Record one background cycle's processed payload. Returns true when the
  // tuned parameters changed (caller re-broadcasts them).
  bool Update(int64_t bytes, double elapsed_sec);

  int64_t fusion_threshold() const { return current_fusion_; }
  double cycle_time_ms() const { return current_cycle_ms_; }
  bool hierarchical() const { return current_hier_; }
  bool cache_enabled() const { return current_cache_; }
  int64_t best_fusion_threshold() const { return best_fusion_; }
  double best_cycle_time_ms() const { return best_cycle_ms_; }
  double best_score() const { return best_score_; }
  int samples() const { return static_cast<int>(ys_.size()); }

 private:
  void Propose();
  double NextRand();

  Options opts_;
  bool done_ = false;
  int cycles_ = 0;
  int64_t bytes_acc_ = 0;
  double time_acc_ = 0;
  int warmup_left_ = 0;
  std::vector<double> window_scores_;  // repeats for the current proposal

  // normalized coords: x0 = log2(fusion)/26, x1 = cycle/25,
  // x2 = hierarchical (0/1), x3 = cache (0/1)
  std::vector<std::vector<double>> xs_;
  std::vector<double> ys_;
  GaussianProcess gp_;

  int64_t current_fusion_ = 64 << 20;
  double current_cycle_ms_ = 1.0;
  bool current_hier_ = false;
  bool current_cache_ = true;
  int64_t best_fusion_ = 64 << 20;
  double best_cycle_ms_ = 1.0;
  bool best_hier_ = false;
  bool best_cache_ = true;
  double best_score_ = -1;
  uint64_t rng_state_ = 12345;
  size_t init_grid_ = 0;  // grid cell of the initial categorical config
  std::ofstream log_;
};

}  // namespace hvd

#endif  // HVD_PARAMETER_MANAGER_H
