"""Utilities: timeline tracing, logging helpers."""
