"""Keras-on-TF helpers (reference: ``horovod/keras/__init__.py``).

``DistributedOptimizer`` wraps a keras optimizer so its gradients are
allreduced before the update (reference ``_impl.create_distributed_
optimizer``, ``horovod/_keras/__init__.py:23-55``), and ``load_model``
restores a saved model while transparently re-wrapping whatever
optimizer it was trained with (reference ``keras/__init__.py:117-150``)
— that is what makes rank-0-restore + broadcast resume work for Keras
models, since the optimizer slot weights come back with the model.
"""

import tensorflow as tf

from horovod_tpu.ops.reduction import Average
from horovod_tpu.tensorflow import Compression, allreduce, size


def DistributedOptimizer(optimizer, name=None, op=Average,
                         compression=Compression.none):
    """Wrap a keras optimizer: ``get_gradients`` (and TF1-style
    ``compute_gradients`` when present) allreduce before returning."""
    cls = type(optimizer)

    class _Distributed(cls):
        _hvd_wrapped = cls

        def get_gradients(self, loss, params):
            grads = super().get_gradients(loss, params)
            if size() <= 1:
                return grads
            return [None if g is None else
                    allreduce(g, op=op, compression=compression,
                              name=f"k.{i}")
                    for i, g in enumerate(grads)]

    _Distributed.__name__ = name or f"Distributed{cls.__name__}"
    # from_config deserializes nested objects (e.g. LearningRateSchedule
    # dicts) that a raw **config constructor call would pass through as
    # garbage (reference _keras/__init__.py uses from_config for this)
    if hasattr(_Distributed, "from_config"):
        return _Distributed.from_config(optimizer.get_config())
    return _Distributed(**optimizer.get_config())


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """``tf.keras.models.load_model`` with every known optimizer class
    mapped to a factory that re-wraps it in DistributedOptimizer
    (reference ``keras/__init__.py:146-150`` ``wrap_optimizer``)."""
    def wrap(cls):
        return lambda **kw: DistributedOptimizer(cls(**kw),
                                                 compression=compression)

    objects = {}
    opt_mod = tf.keras.optimizers
    for attr in dir(opt_mod):
        cls = getattr(opt_mod, attr)
        if isinstance(cls, type):
            objects[attr] = wrap(cls)
    for cls in (custom_optimizers or []):
        objects[cls.__name__] = wrap(cls)
    objects.update(custom_objects or {})
    return tf.keras.models.load_model(filepath, custom_objects=objects)
