"""Framework-neutral reduction-op constants.

The wire-level op names shared by every adapter (JAX, torch, TF, MXNet)
and the native core (reference: ``horovod/common/message.h:46-49`` request
types plus the Min/Max extension). A dependency-free module so adapters
for absent frameworks never drag in another framework at import time.
"""

Sum = "sum"
Average = "average"
Adasum = "adasum"
Min = "min"
Max = "max"
