"""Adasum: scale-insensitive gradient combination (Microsoft).

Reference: header-only templated implementation with AVX fp16 intrinsics and
an MPI recursive vector-halving distance-doubling schedule
(``horovod/common/ops/adasum/adasum.h:186-330`` ``FusedAllreduce``,
pairwise combine at ``adasum.h:331+``; MPI instantiation
``adasum_mpi.cc``; hierarchical GPU variant ``adasum_cuda_operations.cc``).

The pairwise operator for gradients a, b is::

    combined = a * (1 - dot(a,b) / (2*||a||^2))
             + b * (1 - dot(a,b) / (2*||b||^2))

applied recursively over a binary tree of ranks (power-of-2 world size,
same constraint as the reference). TPU-native realization: each tree level
is a full-vector ``ppermute`` exchange with the XOR partner followed by the
combine, entirely inside the compiled step — the dot products and norms are
accumulated in **float32** regardless of wire dtype (the reference needs
hand-written AVX fp16 dot kernels for this; on TPU we just ask XLA for f32
accumulation).

The tree order is identical to the reference's recursive-halving schedule,
so a NumPy reference model (see ``tests/test_adasum.py``) reproduces results
bit-for-bit in f32.
"""

import jax.numpy as jnp
import numpy as np
from jax import lax


def adasum_combine(a, b, eps=0.0):
    """The Adasum pairwise operator (``adasum.h:331+``). Falls back to plain
    sum when either operand has zero norm (matching reference behavior of
    the ratio terms vanishing)."""
    af = a.astype(jnp.float32).ravel()
    bf = b.astype(jnp.float32).ravel()
    dot = jnp.dot(af, bf)
    na2 = jnp.dot(af, af)
    nb2 = jnp.dot(bf, bf)
    ca = jnp.where(na2 > eps, 1.0 - dot / (2.0 * jnp.where(na2 > eps, na2, 1.0)), 1.0)
    cb = jnp.where(nb2 > eps, 1.0 - dot / (2.0 * jnp.where(nb2 > eps, nb2, 1.0)), 1.0)
    out = af * ca + bf * cb
    return out.reshape(a.shape).astype(a.dtype)


def adasum_allreduce(x, axes):
    """Adasum-reduce ``x`` across the shards of ``axes`` (power-of-2 count).

    Tree schedule: at level l each shard exchanges its current vector with
    partner ``rank ^ 2**l`` and both compute the same combined result —
    the distance-doubling pairing of ``adasum.h:186-330`` with full-vector
    exchange instead of vector-halving (bandwidth traded for static shapes
    and zero host coordination; the tree and therefore the numerics are
    identical).
    """
    if isinstance(axes, str):
        axes = (axes,)
    if len(axes) > 1:
        # Hierarchical variant (adasum_cuda_operations.cc): average over the
        # inner (ICI) axes first, Adasum across the outer (DCN) axis.
        outer = axes[0]
        inner = tuple(axes[1:])
        x = lax.pmean(x, inner)
        return adasum_allreduce(x, (outer,))
    axis = axes[0]
    size = lax.axis_size(axis)
    if size & (size - 1):
        raise ValueError(
            f"Adasum requires a power-of-2 number of shards, got {size} "
            "(same constraint as the reference, adasum.h)")
    levels = int(np.log2(size))
    me = lax.axis_index(axis)
    out = x
    for level in range(levels):
        d = 1 << level
        perm = [(i, i ^ d) for i in range(size)]
        other = lax.ppermute(out, axis, perm)
        # Order the operands canonically (lower rank first) so both partners
        # compute the identical combined vector.
        is_low = (me & d) == 0
        a = jnp.where(is_low, out, other)
        b = jnp.where(is_low, other, out)
        out = adasum_combine(a, b)
    return out


def adasum_combine_np(a, b):
    """NumPy reference of the pairwise operator, for tests (pattern of
    ``test/test_adasum_tensorflow.py:33-63`` in the reference: reimplement
    the formula independently and compare)."""
    af = a.astype(np.float32).ravel()
    bf = b.astype(np.float32).ravel()
    dot = float(np.dot(af, bf))
    na2 = float(np.dot(af, af))
    nb2 = float(np.dot(bf, bf))
    ca = 1.0 - dot / (2.0 * na2) if na2 > 0 else 1.0
    cb = 1.0 - dot / (2.0 * nb2) if nb2 > 0 else 1.0
    return (af * ca + bf * cb).reshape(a.shape)


def adasum_tree_np(vectors):
    """NumPy reference of the full tree schedule over a power-of-2 list."""
    vecs = [np.asarray(v, dtype=np.float32) for v in vectors]
    size = len(vecs)
    assert size & (size - 1) == 0
    level = 0
    while (1 << level) < size:
        d = 1 << level
        nxt = list(vecs)
        for i in range(size):
            j = i ^ d
            a, b = (vecs[i], vecs[j]) if i < j else (vecs[j], vecs[i])
            nxt[i] = adasum_combine_np(a, b)
        vecs = nxt
        level += 1
    return vecs[0]
