"""Gradient compression for the collective wire format.

Mirrors ``horovod/torch/compression.py`` / ``horovod/tensorflow/compression.py``
(74 LoC each): a ``Compression`` namespace with ``none`` and ``fp16``
compressors, each exposing ``compress(tensor) -> (tensor, ctx)`` and
``decompress(tensor, ctx) -> tensor``.

TPU-first difference: the narrow wire dtype defaults to **bfloat16** (the
MXU/ICI-native 16-bit format, same exponent range as fp32 so no loss
scaling needed); ``fp16`` is kept as an alias and an explicit
``float16`` compressor is available.
"""

import jax.numpy as jnp


class NoneCompressor:
    """Pass-through (reference ``NoneCompressor``)."""

    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        del ctx
        return tensor


class _CastCompressor:
    """Cast floating tensors to a narrow wire dtype for the collective, cast
    back after (reference ``FP16Compressor``)."""

    def __init__(self, wire_dtype):
        self.wire_dtype = wire_dtype

    def compress(self, tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != self.wire_dtype:
            return tensor.astype(self.wire_dtype), dtype
        return tensor, None

    def decompress(self, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class Compression:
    """Namespace matching the reference API: ``Compression.none``,
    ``Compression.fp16`` (bfloat16 wire on TPU), ``Compression.bf16``,
    ``Compression.float16`` (true IEEE fp16 wire)."""

    none = NoneCompressor()
    bf16 = _CastCompressor(jnp.bfloat16)
    fp16 = bf16  # TPU-native 16-bit wire format
    float16 = _CastCompressor(jnp.float16)
