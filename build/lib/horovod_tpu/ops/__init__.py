"""Collective op implementations (the TPU data plane).

Reference equivalent: ``horovod/common/ops/`` — MPI/NCCL/Gloo/CCL op chains
(``operation_manager.h:26-61``). On TPU there is one backend: XLA collectives
compiled over the device mesh (ICI within a slice, DCN across slices), so
the "op chain" collapses to named-axis primitives plus fusion, compression,
hierarchical, and Adasum layers on top.
"""

from horovod_tpu.ops import collective, compression, fusion, adasum

__all__ = ["collective", "compression", "fusion", "adasum"]
