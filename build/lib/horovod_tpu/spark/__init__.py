"""Spark integration: ``horovod_tpu.spark.run(fn, args=..., num_proc=N)``.

Rebuilds ``horovod/spark/__init__.py:101-236`` as a thin shim over the
pluggable cluster backend (run/cluster.py): Spark owns task placement;
each Spark partition calls back into the driver's signed KV, registers
its NICs + host hash, ring-probes, receives a rank with contiguous
per-host grouping, and runs ``fn``. Results return in rank order.

In-image status: pyspark is not installed here, so this shim is
import-gated and NOT executed by the test suite; the entire protocol
underneath it (registration, probing, host-hash rank grouping, rank
assignment, result collection) IS exercised by
``tests/test_cluster.py`` through LocalProcessBackend, matching how the
reference fakes clusters in ``test/test_spark.py``.
"""

import os

from horovod_tpu.run.cluster import SparkBackend, run_on_cluster


def run(fn, args=(), kwargs=None, num_proc=None, start_timeout=None,
        env=None, verbose=1, nic=None):
    """Run ``fn`` in ``num_proc`` Spark tasks; returns per-rank results
    (reference contract, ``spark/__init__.py:101-130``).

    ``num_proc`` defaults to ``spark.default.parallelism``."""
    import pyspark
    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError("horovod_tpu.spark.run() needs an active "
                           "SparkContext (run inside a PySpark session)")
    if num_proc is None:
        num_proc = sc.defaultParallelism
        if verbose >= 1:
            print(f"Running {num_proc} processes "
                  f"(from spark.default.parallelism)...")
    if start_timeout is None:
        start_timeout = int(os.getenv("HOROVOD_SPARK_START_TIMEOUT", "600"))
    extra = dict(env or {})
    if nic:
        extra["HOROVOD_COMMON_INTERFACES"] = nic
    return run_on_cluster(fn, args=args, kwargs=kwargs, num_proc=num_proc,
                          backend=SparkBackend(sc),
                          start_timeout=start_timeout,
                          extra_env=extra or None)
