"""Host-side runtime: controller, rendezvous, timeline, stall inspector.

The TPU analogue of the reference's C++ core (``horovod/common/``): on TPU
the *data plane* is compiled by XLA, so what remains host-side is the
control plane — process rendezvous and coordination (TCP, no MPI), the
name-negotiated readiness protocol for the eager op path, response caching,
stall detection, the Chrome-trace timeline, and the autotuner. The hot
pieces are implemented natively in C++ (``horovod_tpu/runtime/core/``) and
bound via ctypes.
"""
