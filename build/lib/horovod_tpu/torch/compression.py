"""Gradient wire compression for the torch adapter (reference:
``horovod/torch/compression.py``): cast to fp16 before the collective,
cast back after."""


class NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor:
    @staticmethod
    def compress(tensor):
        if tensor.dtype.is_floating_point:
            return tensor.half(), tensor.dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor.to(ctx) if ctx is not None else tensor


class Compression:
    """Namespace matching ``hvd.Compression.none`` / ``.fp16``."""
    none = NoneCompressor
    fp16 = FP16Compressor
