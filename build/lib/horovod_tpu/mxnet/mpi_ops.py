"""Handle-based collective ops on MXNet NDArrays.

Rebuilds ``horovod/mxnet/mpi_ops.py`` + the engine-async push of
``mxnet/mpi_ops.cc:121-141`` over the native core: NDArrays bridge
through numpy into the name-negotiated queue; ``*_async`` returns a
handle backed by the core's background thread (our analogue of MXNet's
engine var-dependency callback), ``synchronize`` blocks and writes the
result back.

MXNet is not part of this image's baked environment — the module
import-gates on ``mxnet`` and the adapter logic is exercised in-image
against a numpy-backed stand-in (see ``tests/test_mxnet_adapter.py``).
"""

import numpy as np

from horovod_tpu import _core
from horovod_tpu.ops.reduction import Adasum, Average, Max, Min, Sum

_name_counter = {}


def _ensure_core():
    from horovod_tpu import basics
    if not basics.is_initialized():
        raise RuntimeError(
            "horovod_tpu has not been initialized; call hvd.init()")
    if not _core.is_initialized():
        _core.init(rank=0, size=1)


def _auto_name(kind, name):
    if name is not None:
        return name
    n = _name_counter.get(kind, 0)
    _name_counter[kind] = n + 1
    return f"{kind}.noname.{n}"


def _to_numpy(tensor):
    if hasattr(tensor, "asnumpy"):
        return np.ascontiguousarray(tensor.asnumpy())
    return np.ascontiguousarray(tensor)


def _write_back(tensor, arr):
    tensor[:] = arr


class MXHandle:
    """Wraps a core handle; optionally writes the result into an NDArray
    (reference: the engine callback completing the pushed op)."""

    def __init__(self, core_handle, out_tensor=None, make_output=None):
        self._h = core_handle
        self._out = out_tensor
        self._make_output = make_output

    def poll(self):
        return self._h.poll()

    def synchronize(self):
        arr = self._h.wait()
        if self._out is not None:
            _write_back(self._out, arr)
            return self._out
        if self._make_output is not None:
            return self._make_output(arr)
        return arr


def allreduce_async(tensor, average=True, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0):
    _ensure_core()
    op = op or (Average if average else Sum)
    arr = _to_numpy(tensor)
    h = _core.allreduce_async(arr, _auto_name("allreduce", name), op=op,
                              prescale=prescale_factor,
                              postscale=postscale_factor)
    return MXHandle(h, out_tensor=None,
                    make_output=lambda a: _like(tensor, a))


def allreduce_async_(tensor, average=True, name=None, op=None, **kw):
    _ensure_core()
    op = op or (Average if average else Sum)
    arr = _to_numpy(tensor)
    h = _core.allreduce_async(arr, _auto_name("allreduce", name), op=op,
                              **_scales(kw))
    return MXHandle(h, out_tensor=tensor)


def allreduce(tensor, average=True, name=None, op=None, **kw):
    return allreduce_async(tensor, average, name, op, **kw).synchronize()


def allreduce_(tensor, average=True, name=None, op=None, **kw):
    return allreduce_async_(tensor, average, name, op, **kw).synchronize()


def allgather_async(tensor, name=None):
    _ensure_core()
    arr = _to_numpy(tensor)
    h = _core.allgather_async(arr, _auto_name("allgather", name))
    return MXHandle(h, make_output=lambda a: _like(tensor, a))


def allgather(tensor, name=None):
    return allgather_async(tensor, name).synchronize()


def broadcast_async(tensor, root_rank, name=None):
    _ensure_core()
    arr = _to_numpy(tensor)
    h = _core.broadcast_async(arr, _auto_name("broadcast", name),
                              root_rank=root_rank)
    return MXHandle(h, make_output=lambda a: _like(tensor, a))


def broadcast_async_(tensor, root_rank, name=None):
    _ensure_core()
    arr = _to_numpy(tensor)
    h = _core.broadcast_async(arr, _auto_name("broadcast", name),
                              root_rank=root_rank)
    return MXHandle(h, out_tensor=tensor)


def broadcast(tensor, root_rank, name=None):
    return broadcast_async(tensor, root_rank, name).synchronize()


def broadcast_(tensor, root_rank, name=None):
    return broadcast_async_(tensor, root_rank, name).synchronize()


def _like(tensor, arr):
    """Build an output container matching `tensor`'s type (NDArray in,
    NDArray out), falling back to the numpy array."""
    if hasattr(tensor, "asnumpy"):
        try:
            import mxnet as mx
            return mx.nd.array(arr, dtype=arr.dtype)
        except ImportError:
            pass
        cls = type(tensor)
        if hasattr(cls, "from_numpy"):
            return cls.from_numpy(arr)
    return arr


def _scales(kw):
    return {"prescale": kw.get("prescale_factor", 1.0),
            "postscale": kw.get("postscale_factor", 1.0)}
