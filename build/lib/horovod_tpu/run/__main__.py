"""``python -m horovod_tpu.run`` == hvdrun."""
import sys

from horovod_tpu.run.run import main

sys.exit(main())
