"""Host parsing and slot allocation.

Rebuilds ``horovod/run/gloo_run.py:53-111`` (``_allocate``): given a host
spec, produce one slot per process with rank / local_rank / local_size /
cross_rank / cross_size, rank-major by host order.
"""

import dataclasses
import re


@dataclasses.dataclass
class HostSlots:
    hostname: str
    slots: int


@dataclasses.dataclass
class Slot:
    rank: int
    hostname: str
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int
    size: int


def parse_hosts(hosts_str):
    """Parse ``"host1:4,host2:2"`` (reference ``parse_host_files`` /
    ``-H`` handling, run.py:695-760). A bare hostname means 1 slot."""
    out = []
    for part in hosts_str.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append(HostSlots(name, int(slots)))
        else:
            out.append(HostSlots(part, 1))
    return out


def parse_hostfile(path):
    """Hostfile lines: ``hostname slots=N`` (mpirun-style, reference
    run.py hostfile support)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)(?:\s+slots\s*=\s*(\d+))?$", line)
            if not m:
                raise ValueError(f"bad hostfile line: {line!r}")
            out.append(HostSlots(m.group(1), int(m.group(2) or 1)))
    return out


def allocate(hosts, np):
    """Assign ``np`` ranks to hosts' slots, host-major.

    cross_rank/cross_size mirror the reference: for a given local_rank,
    cross_size = number of hosts that have that local_rank filled, and
    cross_rank = this host's index among them (gloo_run.py:84-108).
    """
    total = sum(h.slots for h in hosts)
    if np > total:
        raise ValueError(
            f"requested {np} processes but hosts provide only {total} slots")
    slots = []
    rank = 0
    per_host = []  # (hostname, ranks-on-host)
    for h in hosts:
        n = min(h.slots, np - rank)
        if n <= 0:
            break
        per_host.append((h.hostname, list(range(rank, rank + n))))
        rank += n
    for host_idx, (hostname, ranks) in enumerate(per_host):
        for lr, r in enumerate(ranks):
            # hosts that have a process with this local_rank
            hosts_with_lr = [i for i, (_, rr) in enumerate(per_host)
                             if lr < len(rr)]
            slots.append(Slot(
                rank=r, hostname=hostname, local_rank=lr,
                local_size=len(ranks),
                cross_rank=hosts_with_lr.index(host_idx),
                cross_size=len(hosts_with_lr), size=np))
    slots.sort(key=lambda s: s.rank)
    return slots
