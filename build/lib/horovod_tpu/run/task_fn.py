"""Per-host discovery task, launched by hvdrun before the training job.

The reference launches ``horovod/run/task_fn.py:1-67`` on every host via
ssh: it registers the host's candidate addresses with the driver, ring-
probes its successor with interface matching, and exits.  This module is
the same protocol over the signed KV (run/discovery.py); hvdrun runs one
instance per *host* and then feeds the elected common interfaces into
every worker's environment.

Usage (spawned by run.py, not by hand)::

    python -m horovod_tpu.run.task_fn <index> <num_hosts> <kv_addr> <kv_port>

The per-run HMAC key arrives via the environment (HOROVOD_SECRET_KEY),
like the reference's ``_HOROVOD_SECRET_KEY``.
"""

import sys

from horovod_tpu.run import secret as _secret
from horovod_tpu.run.discovery import TaskAgent


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) not in (4, 5):
        print("usage: task_fn <index> <num_hosts> <kv_addr> <kv_port> "
              "[timeout_s]", file=sys.stderr)
        return 1
    index, num_hosts = int(argv[0]), int(argv[1])
    kv_addr, kv_port = argv[2], int(argv[3])
    timeout = float(argv[4]) if len(argv) == 5 else 600.0
    key = _secret.key_from_env()
    if key is None:
        print("task_fn: HOROVOD_SECRET_KEY not set", file=sys.stderr)
        return 1
    agent = TaskAgent(index, num_hosts, kv_addr, kv_port, key)
    try:
        agent.register()
        agent.run_ring_probe(timeout=timeout)
        # block until the driver publishes the verdict so the ping server
        # stays up for any still-probing predecessor
        agent.common_interfaces(timeout=timeout)
    finally:
        agent.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
