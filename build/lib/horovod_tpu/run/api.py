"""Programmatic launcher: ``run(fn, args=(), np=N)``.

Rebuilds the reference's interactive API (``horovod.run.run()``,
``horovod/run/run.py:857-953``): pickle a function, ship it to N freshly
launched worker processes through the KV server, execute it under the full
env contract, collect per-rank results in rank order.
"""

import os
import pickle
import sys

from horovod_tpu.run import allocation, launcher
from horovod_tpu.run import secret as _secret
from horovod_tpu.run.rendezvous import KVStoreServer, kv_wait

try:  # cloudpickle handles closures/lambdas; stdlib pickle is the fallback
    import cloudpickle as _pickler
except ImportError:  # pragma: no cover
    _pickler = pickle


def run(fn, args=(), kwargs=None, np=1, hosts=None, extra_env=None,
        timeout=300, use_jax_coordinator=False):
    """Run ``fn(*args, **kwargs)`` in ``np`` horovod_tpu processes and
    return the list of per-rank return values (rank order)."""
    kwargs = kwargs or {}
    host_list = (allocation.parse_hosts(hosts) if hosts
                 else [allocation.HostSlots("localhost", np)])
    slots = allocation.allocate(host_list, np)

    controller_addr = slots[0].hostname
    if controller_addr in launcher.LOCAL_HOSTS:
        controller_addr = "127.0.0.1"
    controller_port = 0  # rank 0 binds + publishes via the KV server

    all_local = all(s.hostname in launcher.LOCAL_HOSTS for s in slots)
    # multi-host: per-run HMAC key so no unauthenticated peer can feed
    # pickles into the KV (reference secret.py contract)
    auth_key = None if all_local else _secret.make_secret_key()
    kv = KVStoreServer(host="127.0.0.1" if all_local else "0.0.0.0",
                       auth_key=auth_key)
    rendezvous_port = kv.start()
    kv.put("runfunc/func", _pickler.dumps((fn, args, kwargs)))

    env = dict(extra_env or {})
    if auth_key is not None:
        env[_secret.SECRET_ENV] = _secret.encode_key(auth_key)
    env["PYTHONPATH"] = launcher.repo_pythonpath()
    if use_jax_coordinator:
        from horovod_tpu.run.run import free_port
        env["HOROVOD_COORDINATOR_ADDR"] = (
            f"{controller_addr}:{free_port()}")

    command = [sys.executable, "-m", "horovod_tpu.run.run_task"]
    job = launcher.launch(slots, command, controller_addr, controller_port,
                          rendezvous_port=rendezvous_port, extra_env=env)
    try:
        try:
            job.wait()
        except RuntimeError as e:
            # surface the failed rank's shipped traceback when available
            for r in range(np):
                payload = kv.get(f"runfunc/result/{r}")
                if payload is None:
                    continue
                ok, value = pickle.loads(payload)
                if not ok:
                    raise RuntimeError(
                        f"rank {r} raised:\n{value}") from e
            raise
        results = []
        for r in range(np):
            payload = kv_wait("127.0.0.1", rendezvous_port,
                              f"runfunc/result/{r}", timeout=timeout,
                              auth_key=auth_key)
            ok, value = pickle.loads(payload)
            if not ok:
                raise RuntimeError(f"rank {r} raised:\n{value}")
            results.append(value)
        return results
    finally:
        kv.stop()
