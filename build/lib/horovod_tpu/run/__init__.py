"""hvdrun — the process launcher (reference: ``horovod/run/``).

Starts one training process per slot across hosts with the
``HOROVOD_RANK/SIZE/LOCAL_RANK/...`` env contract
(``horovod/run/gloo_run.py:210-236``), a TCP controller endpoint for the
native core, and an HTTP rendezvous/KV server. No MPI anywhere — TPU VMs
don't have it; plain subprocess + ssh, like the reference's Gloo path.

Entry points:
* CLI: ``hvdrun -np 4 python train.py`` (also
  ``python -m horovod_tpu.run``)
* programmatic: ``horovod_tpu.run.run(fn, args=(), np=4)``
  (reference: ``horovod.run.run()``, run.py:857-953)
"""

from horovod_tpu.run.api import run
from horovod_tpu.run.run import main, parse_args

__all__ = ["run", "main", "parse_args"]
