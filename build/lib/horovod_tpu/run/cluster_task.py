"""Entry point for one cluster executor slot (reference role:
``horovod/spark/task/mpirun_exec_fn.py`` + ``spark/__init__.py:36-68``
``_task_fn``): register, probe, receive rank assignment, run the shipped
function. Launched by LocalProcessBackend; SparkBackend calls
``cluster.cluster_task`` in-process inside the Spark partition instead.

Usage: python -m horovod_tpu.run.cluster_task <index> <n> <kv_addr> <kv_port>
The per-run key arrives via HOROVOD_SECRET_KEY.
"""

import os
import sys

from horovod_tpu.run import secret as _secret
from horovod_tpu.run.cluster import cluster_task


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 4:
        print("usage: cluster_task <index> <num_tasks> <kv_addr> <kv_port>",
              file=sys.stderr)
        return 1
    key_hex = os.environ.get(_secret.SECRET_ENV)
    if not key_hex:
        print("cluster_task: HOROVOD_SECRET_KEY not set", file=sys.stderr)
        return 1
    ctx = {"kv_addr": argv[2], "kv_port": int(argv[3]), "key": key_hex}
    cluster_task(int(argv[0]), int(argv[1]), ctx)
    return 0


if __name__ == "__main__":
    sys.exit(main())
