"""Small example models: the MNIST-scale nets of the reference's examples.

Reference: the ``Net`` in ``/root/reference/examples/pytorch_mnist.py:44-60``
(conv-conv-fc-fc with dropout) and the Keras MNIST models
(``examples/keras_mnist.py``). These are fresh flax implementations with the
same capacity class, used by ``examples/`` and the MNIST tests.
"""

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class MNISTConvNet(nn.Module):
    """conv(32) -> conv(64) -> fc(128) -> fc(10), the classic MNIST net."""
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class MLP(nn.Module):
    """Plain MLP for smoke tests and the linear-regression examples."""
    features: Sequence[int] = (128, 128, 10)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        del train
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        for i, f in enumerate(self.features):
            x = nn.Dense(f, dtype=self.dtype)(x)
            if i < len(self.features) - 1:
                x = nn.relu(x)
        return x.astype(jnp.float32)
