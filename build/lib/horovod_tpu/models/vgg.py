"""VGG-16, the bandwidth-bound member of the reference's benchmark trio.

Reference baseline: 68% scaling efficiency at 512 GPUs (``README.rst:77``) —
VGG's 138M mostly-fc parameters stress gradient-allreduce bandwidth, which
is exactly what the fusion + hierarchical-reduction paths exist for. Fresh
flax implementation (the reference uses tf_cnn_benchmarks' VGG).
"""

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# channels per conv stage; 'M' marks max-pool (the standard VGG-16 "D" cfg)
_VGG16_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
              512, 512, 512, "M", 512, 512, 512, "M")


class VGG16(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    cfg: Sequence = _VGG16_CFG

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(4096, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
