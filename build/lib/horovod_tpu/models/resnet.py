"""ResNet v1.5 family in flax.linen, TPU-first.

The benchmark model of the reference's headline numbers (ResNet-50/101 via
``tf_cnn_benchmarks --variable_update horovod`` and torchvision
``models.resnet50`` in ``/root/reference/examples/
pytorch_synthetic_benchmark.py:24``; numbers in ``docs/benchmarks.rst``).
Implemented fresh for TPU:

* NHWC layout (XLA's native conv layout on TPU), bf16 compute / fp32
  params so convs land on the MXU at full rate.
* v1.5 variant (stride on the 3x3 of the bottleneck, like torchvision) so
  accuracy/throughput is comparable with the reference benchmarks.
* BatchNorm stats are local to each data shard by default (exactly the
  reference's semantics: each Horovod rank normalizes over its own
  sub-batch); pass ``bn_cross_replica_axes`` to opt into synchronized BN
  over mesh axes.
"""

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3(stride) -> 1x1 bottleneck (ResNet-50/101/152, v1.5)."""
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # zero-init the last BN scale: residual branch starts as identity
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet v1.5 over NHWC inputs.

    ``dtype`` is the compute dtype (bf16 default — MXU native); parameters
    stay fp32. ``bn_cross_replica_axes`` turns on sync-BN over the given
    mesh axes (inside shard_map); None keeps per-shard stats like the
    reference's per-rank BN.
    """
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    bn_cross_replica_axes: Optional[Tuple[str, ...]] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       padding="SAME")
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       axis_name=self.bn_cross_replica_axes)
        act = nn.relu

        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(self.num_filters * 2 ** i, conv=conv,
                                   norm=norm, act=act, strides=strides)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.dtype,
                     name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckBlock)
